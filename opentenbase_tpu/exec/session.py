"""Single-node engine + session: the "centralized mode" of the reference
(IS_CENTRALIZED_MODE, src/include/pgxc/pgxc.h:111-117 — one node acting as
access node and datanode at once).  The distributed CN/DN split layers on
top of this engine in net/ and parallel/.

A LocalNode owns: catalog, table stores, WAL, a device cache, and a local
timestamp source (stand-in for the GTM; the gtm/ service replaces it in
cluster mode).  Session wraps it with the SQL statement loop
(reference: exec_simple_query, tcop/postgres.c:1370).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional

import numpy as np

from ..catalog.catalog import Catalog, CatalogError
from ..catalog.schema import DistType, NodeDef, TableDef
from ..catalog.types import TypeKind
from ..obs import trace as obs_trace
from ..parallel.locator import Locator
from ..plan import physical as P
from ..plan.planner import PlannedStmt, Planner
from ..sql import ast as A
from ..sql.analyze import Binder, split_conjuncts
from ..sql.ddl import sequence_def_from_ast, table_def_from_ast
from ..sql.parser import parse_sql
from ..storage.store import TableStore
from ..storage.wal import Wal, checkpoint_store, restore_store
from .executor import (DBatch, DeviceTableCache, ExecContext, ExecError,
                       Executor, materialize)


@dataclasses.dataclass
class Result:
    """One statement's result."""
    command: str
    names: list[str] = dataclasses.field(default_factory=list)
    rows: list[tuple] = dataclasses.field(default_factory=list)
    rowcount: int = 0
    text: str = ""                      # EXPLAIN etc.


def _text_log_array(v) -> np.ndarray:
    """WAL representation of a TEXT column: must be a string-kind array —
    numeric-looking values (zip codes) logged as ints would be mistaken
    for dictionary codes at recovery."""
    arr = np.asarray(v)
    if arr.dtype.kind in "SU":
        return arr
    return np.asarray([str(x) for x in v])


def replay_alter(catalog, stores: dict, rec: dict) -> None:
    """WAL replay of an ALTER TABLE record (shared by the single-node
    and datanode recovery paths)."""
    table = rec["table"]
    act = rec["action"]
    st = stores.get(table)
    if act == "rename_table":
        if catalog is not None and table in catalog.tables:
            catalog.tables[rec["new_name"]] = catalog.tables.pop(table)
            catalog.tables[rec["new_name"]].name = rec["new_name"]
        if table in stores:
            stores[rec["new_name"]] = stores.pop(table)
        return
    if st is None:
        return
    if act == "add_column":
        from ..catalog import types as T
        from ..catalog.schema import ColumnDef
        name, tname, targs = rec["column"]
        st.alter_add_column(
            ColumnDef(name, T.type_from_name(tname, tuple(targs))))
    elif act == "drop_column":
        st.alter_drop_column(rec["name"])
    elif act == "rename_column":
        st.alter_rename_column(rec["name"], rec["new_name"])


def conform_replay_columns(st, enc: dict, n: int, nulls):
    """An insert WAL record written before an ALTER may lack new
    columns (-> all-NULL fill) or carry dropped ones (-> ignore)."""
    enc = {c: v for c, v in enc.items() if st.td.has_column(c)}
    missing = [c for c in st.td.columns if c.name not in enc]
    if missing:
        nulls = dict(nulls or {})
        for c in missing:
            enc[c.name] = np.zeros((n, *c.type.shape_suffix),
                                   c.type.np_dtype)
            nulls[c.name] = np.ones(n, dtype=bool)
    return enc, (nulls or None)


def copy_rows_to_file(path: str, rows, delim: str) -> int:
    """COPY ... TO: delimiter-separated text, NULL spelled \\N, with
    backslash/delimiter/newline escaping so any value round-trips (the
    reference's text format, commands/copy.c CopyAttributeOutText)."""
    def esc(v):
        if v is None:
            return "\\N"
        s = str(v)
        return (s.replace("\\", "\\\\").replace(delim, "\\" + delim)
                 .replace("\n", "\\n"))

    n = 0
    with open(path, "w") as f:
        for row in rows:
            f.write(delim.join(esc(v) for v in row))
            f.write("\n")
            n += 1
    return n


def copy_to_select(table: str, cols) -> A.SelectStmt:
    """The SELECT a COPY TO reads through (shared by the single-node
    and cluster sessions)."""
    return A.SelectStmt(
        items=[A.SelectItem(A.ColRef((c,))) for c in cols],
        from_=[A.TableRef(table)])


def _in_list(table: str, col: str, keys) -> A.Node:
    """col IN (k1, k2, ...) qual for MERGE's matched-key DML."""
    consts = []
    for k in keys:
        if isinstance(k, bool):
            consts.append(A.Const(k, "bool"))
        elif isinstance(k, (int, np.integer)):
            consts.append(A.Const(int(k), "int"))
        elif isinstance(k, (float, np.floating)):
            consts.append(A.Const(repr(float(k)), "num"))
        else:
            consts.append(A.Const(str(k), "str"))
    return A.InExpr(A.ColRef((table, col)), consts, None, False)


class TxnState:
    def __init__(self, txid: int, snapshot_ts: int):
        self.txid = txid
        self.snapshot_ts = snapshot_ts
        # per-store write sets for commit/abort backfill
        self.insert_spans: list[tuple[TableStore, list]] = []
        self.delete_spans: list[tuple[TableStore, tuple]] = []
        self.lock_spans: list[tuple[TableStore, tuple]] = []
        self.explicit = False
        self.wal_ops = 0          # WAL-visible ops (for subabort keep)
        # name -> (ins_len, del_len, lock_len, wal_ops), insert-ordered
        self.savepoints: dict[str, tuple] = {}


class LocalGts:
    """Monotonic local timestamp source — the in-process stand-in for the
    GTM (reference: GetGlobalTimestampGTM, access/transam/gtm.c:1962).
    Cluster mode swaps in gtm/client.py with the same interface."""

    def __init__(self, start: int = 100):
        # the serving tier (exec/scheduler.py) draws snapshots from
        # concurrent dispatch threads; unlocked += would drop grants
        self._lock = threading.Lock()
        self._ts = start
        self._txid = 1

    def next_gts(self) -> int:
        with self._lock:
            self._ts += 1
            return self._ts

    def next_txid(self) -> int:
        with self._lock:
            self._txid += 1
            return self._txid


class LocalNode:
    def __init__(self, datadir: Optional[str] = None, node_name: str = "dn0"):
        self.catalog = Catalog()
        self.catalog.register_node(NodeDef(node_name, "datanode", index=0))
        self.catalog.build_default_shard_map(1)
        self.stores: dict[str, TableStore] = {}
        self.active_txns: set[int] = set()
        self.gts = LocalGts()
        from ..storage.lockmgr import LockManager
        self.lockmgr = LockManager()
        self.lock_timeout = 10.0
        self.cache = DeviceTableCache()
        self.datadir = datadir
        self.wal: Optional[Wal] = None
        self.gucs: dict[str, str] = {
            "enable_fast_query_shipping": "on",
            "enable_datanode_push": "on",
        }
        if datadir:
            os.makedirs(datadir, exist_ok=True)
            # restarts of a durable node skip XLA compiles entirely:
            # the compiled-program store lives next to the data
            from .plancache import enable_persistent_cache
            enable_persistent_cache(os.path.join(datadir, "xla-cache"))
            self._recover()
            self.wal = Wal(os.path.join(datadir, "wal.log"))

    # ---- persistence ----
    def _recover(self):
        # clock state first: recovered rows carry commit GTS that must be
        # in this node's past (reference: pg_control checkpoint record +
        # GTM's persistent store gtm_store.c)
        metapath = os.path.join(self.datadir, "meta.json")
        if os.path.exists(metapath):
            import json
            with open(metapath) as f:
                meta = json.load(f)
            self.gts._ts = max(self.gts._ts, meta["gts"])
            self.gts._txid = max(self.gts._txid, meta["txid"])
        catpath = os.path.join(self.datadir, "catalog.json")
        if os.path.exists(catpath):
            self.catalog = Catalog.load(catpath)
            for name, td in self.catalog.tables.items():
                st = TableStore(td)
                ckpt = os.path.join(self.datadir, f"{name}.ckpt")
                if os.path.exists(ckpt):
                    restore_store(st, ckpt)
                    # checkpoint older than ALTER ADD COLUMN: reconcile
                    for c in td.columns:
                        st.alter_add_column(c)
                self.stores[name] = st
        walpath = os.path.join(self.datadir, "wal.log")
        replayed: dict[int, list] = {}
        for rec in Wal.replay(walpath):
            self._replay_record(rec, replayed)

    def _replay_record(self, rec: dict, pending: dict):
        op = rec.get("op")
        # never reuse any txid seen in the log: a crashed (uncommitted) txn's
        # rows would become visible to a new txn that drew the same id
        if "txid" in rec:
            self.gts._txid = max(self.gts._txid, rec["txid"])
        if op == "create_table":
            td = TableDef.from_json(rec["table"])
            if td.name not in self.catalog.tables:
                self.catalog.create_table(td)
            self.stores.setdefault(td.name, TableStore(td))
        elif op == "drop_table":
            self.catalog.drop_table(rec["name"], if_exists=True)
            self.stores.pop(rec["name"], None)
            self.catalog.partitioned.pop(rec["name"], None)
            for pi in self.catalog.partitioned.values():
                pi["parts"] = [p for p in pi["parts"]
                               if p["name"] != rec["name"]]
        elif op == "insert":
            st = self.stores[rec["table"]]
            enc = {}
            for cname, v in rec["columns"].items():
                if not st.td.has_column(cname):
                    continue      # column dropped after this record
                arr = np.asarray(v)
                if arr.dtype.kind == "S":
                    enc[cname] = st.encode_column(cname, arr)
                elif arr.dtype.kind in "UO":
                    # TEXT columns are logged as raw strings (dictionary
                    # codes are not stable across restarts)
                    enc[cname] = st.encode_column(cname, list(arr))
                else:
                    # all other columns were logged in storage
                    # representation — re-encoding would double-scale
                    # decimals
                    if st.td.has_column(cname):
                        enc[cname] = arr.astype(
                            st.td.column(cname).type.np_dtype)
            enc, nulls = conform_replay_columns(st, enc, rec["n"],
                                                rec.get("nulls"))
            spans = st.insert(enc, rec["n"], rec["txid"], nulls=nulls)
            pending.setdefault(rec["txid"], []).append(("ins", st, spans))
        elif op == "delete":
            st = self.stores[rec["table"]]
            span = st.mark_delete(rec["chunk"],
                                  np.asarray(rec["mask"]), rec["txid"])
            pending.setdefault(rec["txid"], []).append(("del", st, span))
        elif op == "commit":
            ts = np.int64(rec["ts"])
            for kind, st, sp in pending.pop(rec["txid"], []):
                if kind == "ins":
                    st.backfill_insert(sp, ts)
                else:
                    st.backfill_delete([sp], ts)
            self.gts._ts = max(self.gts._ts, int(rec["ts"]))
            self.gts._txid = max(self.gts._txid, rec["txid"])
        elif op == "abort":
            for kind, st, sp in pending.pop(rec["txid"], []):
                if kind == "ins":
                    st.abort_insert(sp)
                else:
                    st.revert_delete([sp])
        elif op == "partition_parent":
            self.catalog.partitioned[rec["table"]] = {
                "method": rec["method"], "key": rec["key"], "parts": []}
        elif op == "create_partition":
            self.catalog.partitioned[rec["parent"]]["parts"].append(
                rec["rec"])
        elif op == "create_view":
            self.catalog.views[rec["name"]] = rec["text"]
        elif op == "trigger_ddl":
            self.catalog.functions = dict(rec["functions"])
            self.catalog.triggers = dict(rec["triggers"])
        elif op == "security_ddl":
            self.catalog.masks = dict(rec["masks"])
            self.catalog.fga_policies = dict(rec["fga"])
        elif op == "drop_view":
            self.catalog.views.pop(rec["name"], None)
        elif op == "alter_table":
            replay_alter(self.catalog, self.stores, rec)
        elif op == "truncate":
            st = self.stores.get(rec["table"])
            if st is not None:
                st.truncate()
        elif op == "create_node_group":
            if rec["name"] not in self.catalog.node_groups:
                self.catalog.create_node_group(rec["name"],
                                               rec["members"])
        elif op == "subabort":
            # ROLLBACK TO SAVEPOINT: revert this txn's ops beyond the
            # savepoint's WAL position (reference: subxact abort
            # records, xact.c)
            lst = pending.get(rec["txid"], [])
            undo = lst[rec["keep"]:]
            del lst[rec["keep"]:]
            for kind, st, sp in undo:
                if kind == "ins":
                    st.abort_insert(sp)
                else:
                    st.revert_delete([sp])

    def checkpoint(self) -> bool:
        if not self.datadir:
            return False
        if self.active_txns:
            # truncating the WAL would orphan in-flight txns' records: a
            # later COMMIT would replay against nothing (the reference's
            # checkpointer coordinates with open xacts via the proc array)
            return False
        import json
        self.catalog.save(os.path.join(self.datadir, "catalog.json"))
        for name, st in self.stores.items():
            checkpoint_store(st, os.path.join(self.datadir, f"{name}.ckpt"))
        tmp = os.path.join(self.datadir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"gts": self.gts._ts, "txid": self.gts._txid}, f)
        os.replace(tmp, os.path.join(self.datadir, "meta.json"))
        if self.wal:
            self.wal.truncate()
        return True

    def _log(self, rec: dict, sync: bool = False):
        if self.wal:
            self.wal.append(rec, sync=sync)

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              users_path: Optional[str] = None, **knobs):
        """Thin serving-tier facade: start a CN wire server whose
        connections each get a Session over this node, with every
        statement routed through the admission/batching scheduler
        (exec/scheduler.py).  Returns (server, scheduler)."""
        from .scheduler import serve
        return serve(self, host=host, port=port,
                     users_path=users_path, **knobs)


def _trace_explain_lines() -> str:
    """EXPLAIN ANALYZE footer from the open query trace: staging,
    program-cache, buffer-pool and exchange activity of the inner run
    (empty when OTB_TRACE=0 — the per-node actuals don't need it)."""
    qt = obs_trace.current_trace()
    if qt is None:
        return ""
    lines = [
        f"Stage: {qt.phase_ms('stage'):.2f} ms "
        f"({int(qt.sum_attr('upload', 'bytes'))} bytes uploaded)",
        f"Programs: hits={qt.count_events('program', hit=True)} "
        f"compiles={qt.count_events('compile')} "
        f"compile_ms={qt.sum_attr('compile', 'ms'):.1f}",
        f"Buffer Pool: hits={qt.count_events('pool', hit=True)} "
        f"misses={qt.count_events('pool', hit=False)}",
    ]
    rounds = int(qt.sum_attr("exchange", "rounds"))
    if rounds:
        lines.append(
            f"Exchanges: rounds={rounds} "
            f"bytes={int(qt.sum_attr('exchange', 'bytes'))} "
            f"time={qt.phase_ms('exchange'):.2f} ms")
    # cluster tier over TCP: per-DN phase timings from the span
    # subtrees each server piggy-backed on its replies — real remote
    # stage/execute time, not the CN-observed RPC wall total
    from ..obs import xray as obs_xray
    for node, a in obs_xray.remote_rows(qt):
        parts = [f"rpcs={a.get('rpcs', 0)}",
                 f"server={a.get('server_ms', 0.0):.2f} ms"]
        for ph in obs_trace.PHASES:
            if a.get(ph):
                parts.append(f"{ph}={a[ph]:.2f} ms")
        lines.append(f"Remote {node}: " + " ".join(parts))
    return "".join("\n" + ln for ln in lines)


class Session:
    def __init__(self, node: LocalNode):
        self.node = node
        self.txn: Optional[TxnState] = None
        self.txn_aborted = False
        # out-of-band cancel (CnServer wires the cancel-protocol peer to
        # this; the scheduler propagates it into queued/batched items)
        self.cancel_event = threading.Event()

    # ------------------------------------------------------------------
    def _check_interrupts(self, deadline: Optional[float]):
        """Statement-boundary interrupt poll (CHECK_FOR_INTERRUPTS):
        consume a pending cancel, enforce the statement deadline."""
        if self.cancel_event.is_set():
            self.cancel_event.clear()
            raise ExecError("canceling statement due to user request")
        if deadline is not None and time.monotonic() >= deadline:
            from ..obs import xray as obs_xray
            obs_xray.flight("statement_timeout")
            raise ExecError(
                "canceling statement due to statement timeout")

    def _stmt_deadline(self) -> Optional[float]:
        """Absolute deadline from the statement_timeout GUC (PG
        semantics: milliseconds, 0/unset disabled)."""
        raw = str(self.node.gucs.get("statement_timeout", "")
                  or "").strip()
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            return None
        return time.monotonic() + ms / 1e3 if ms > 0 else None

    def execute(self, sql: str) -> list[Result]:
        out = []
        self._cur_sql = sql.strip()
        deadline = self._stmt_deadline()
        for s in parse_sql(sql):
            self._check_interrupts(deadline)
            if self.txn is not None and self.txn_aborted \
                    and not isinstance(s, A.TxnStmt) \
                    and not (isinstance(s, A.SavepointStmt)
                             and s.op == "rollback_to"):
                raise ExecError(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            try:
                out.append(self._exec_retryable(s))
            except Exception:
                if self.txn is not None and not self.txn_aborted \
                        and not isinstance(s, A.TxnStmt):
                    self.txn_aborted = True
                    if not self.txn.savepoints:
                        # abort NOW: writes revert and locks release
                        # immediately (PG: AbortCurrentTransaction).
                        # With live savepoints the txn must survive
                        # for ROLLBACK TO, so only poison it.
                        self._abort(self.txn)
                        self.txn.rolled_back = True
                raise
        return out

    def _exec_retryable(self, s: A.Node) -> Result:
        """Implicit (single-statement) transactions retry with a FRESH
        snapshot when a concurrent writer committed first — the
        READ COMMITTED re-check; explicit transactions surface the
        serialization error (REPEATABLE READ semantics, PG's 'could
        not serialize access due to concurrent update')."""
        from ..storage.store import SerializationConflict
        sig = getattr(self, "_cur_sql", "") or type(s).__name__
        with obs_trace.trace_query(sig[:200]) as qt:
            if qt is not None:
                self._last_trace = qt
            for _attempt in range(100):
                try:
                    return self._exec_stmt(s)
                except SerializationConflict as e:
                    if self.txn is not None:
                        raise ExecError(str(e)) from None
                    continue
            raise ExecError(
                "could not serialize access due to concurrent update "
                "(retries exhausted)")

    def query(self, sql: str) -> list[tuple]:
        """Convenience: single SELECT -> rows."""
        res = self.execute(sql)
        return res[-1].rows

    def last_query_stats(self) -> dict:
        """Trace-backed per-phase breakdown of the most recent
        statement on this session (plan/stage/execute/finalize ms,
        rows, bytes, pool hit counts).  Empty when OTB_TRACE=0."""
        qt = getattr(self, "_last_trace", None)
        return qt.summary() if qt is not None else {}

    @property
    def last_stage_ms(self) -> float:
        # deprecated alias: staging time now comes from the trace
        # (kept for callers that predate last_query_stats()).  Reports
        # the overlap-ADJUSTED wait (stage_wait_ms) so pipelined
        # staging hidden behind device compute doesn't count as time
        # this statement stalled; falls back to raw stage_ms for
        # traces without overlap attribution.
        st = self.last_query_stats()
        return float(st.get("stage_wait_ms", st.get("stage_ms", 0.0)))

    # ------------------------------------------------------------------
    def _begin_implicit(self) -> tuple[TxnState, bool]:
        if self.txn is not None:
            return self.txn, False
        t = TxnState(self.node.gts.next_txid(), self.node.gts.next_gts())
        return t, True

    def _track_write(self, t: TxnState):
        """Register a txn as having in-flight WAL records (blocks
        checkpoint truncation until commit/abort)."""
        self.node.active_txns.add(t.txid)

    def _commit(self, t: TxnState):
        ts = np.int64(self.node.gts.next_gts())
        self.node._log({"op": "commit", "txid": t.txid, "ts": int(ts)},
                       sync=True)
        for st, spans in t.insert_spans:
            st.backfill_insert(spans, ts)
        for st, span in t.delete_spans:
            st.backfill_delete([span], ts)
        for st, span in t.lock_spans:
            st.clear_locks([span])
        from ..utils import snapcheck
        if snapcheck.history_on() and (t.insert_spans or t.delete_spans):
            # SI history: post-backfill store versions tagged with the
            # commit GTS — the write half analysis/sicheck.py orders by
            snapcheck.note_write(
                t.txid, int(ts),
                {st.td.name: st.version
                 for st, _sp in (t.insert_spans + t.delete_spans)})
        self.node.active_txns.discard(t.txid)
        self.node.lockmgr.resolve(t.txid, committed=True)

    def _abort(self, t: TxnState):
        self.node._log({"op": "abort", "txid": t.txid})
        for st, spans in t.insert_spans:
            st.abort_insert(spans)
        for st, span in t.delete_spans:
            st.revert_delete([span])
        for st, span in t.lock_spans:
            st.clear_locks([span])
        self.node.active_txns.discard(t.txid)
        self.node.lockmgr.resolve(t.txid, committed=False)

    # ------------------------------------------------------------------
    def _fire_triggers(self, t, implicit: bool, table: str,
                       timing: str, event: str, rows_new, rows_old,
                       colnames):
        """Fire row triggers inside txn `t` (installed as the session
        txn for the duration so body statements join it — a trigger
        failure aborts the whole DML statement)."""
        from .triggers import fire
        installed = False
        if implicit and self.txn is None:
            self.txn = t
            installed = True
        try:
            fire(self, self.node.catalog, table, timing, event,
                 rows_new, rows_old, colnames)
        finally:
            if installed:
                self.txn = None

    def _exec_stmt(self, stmt: A.Node) -> Result:
        from .security import _SECURITY_DDL
        from .security import ddl as security_ddl
        if isinstance(stmt, _SECURITY_DDL):
            self.node.ddl_gen = getattr(self.node, "ddl_gen", 0) + 1
            tag = security_ddl(self.node.catalog, stmt)
            self.node._log({"op": "security_ddl",
                            "masks": self.node.catalog.masks,
                            "fga": self.node.catalog.fga_policies},
                           sync=True)
            return Result(tag)
        from .triggers import _TRIGGER_DDL
        from .triggers import ddl as trigger_ddl
        if isinstance(stmt, _TRIGGER_DDL):
            self.node.ddl_gen = getattr(self.node, "ddl_gen", 0) + 1
            tag = trigger_ddl(self.node.catalog, stmt)
            self.node._log({"op": "trigger_ddl",
                            "functions": self.node.catalog.functions,
                            "triggers": self.node.catalog.triggers},
                           sync=True)
            return Result(tag)
        if isinstance(stmt, (A.CreateTableStmt, A.DropTableStmt,
                             A.AlterTableStmt, A.CreateViewStmt,
                             A.DropViewStmt, A.CreatePartitionStmt,
                             A.CreateIndexStmt, A.DropIndexStmt,
                             A.AnalyzeStmt)):
            # any schema/stats change invalidates cached plans
            self.node.ddl_gen = getattr(self.node, "ddl_gen", 0) + 1
        if isinstance(stmt, (A.SelectStmt, A.InsertStmt, A.ExplainStmt)):
            from .recursive import expand_in_stmt
            stmt2, cleanup = expand_in_stmt(self, stmt)
            if stmt2 is not stmt:
                try:
                    return self._exec_stmt(stmt2)
                finally:
                    cleanup()
        if isinstance(stmt, A.SelectStmt):
            return self._exec_select(stmt)
        if isinstance(stmt, A.CreateTableStmt):
            td = table_def_from_ast(stmt)
            if stmt.partition_by and not any(
                    c.name == stmt.partition_by[1] for c in td.columns):
                raise ExecError(f"partition key "
                                f"{stmt.partition_by[1]!r} not in table")
            self.node.catalog.create_table(td, stmt.if_not_exists)
            self.node.stores.setdefault(td.name, TableStore(td))
            self.node._log({"op": "create_table", "table": td.to_json()},
                           sync=True)
            if stmt.partition_by:
                from ..parallel.partition import (PartitionError,
                                                  register_parent)
                try:
                    register_parent(self.node.catalog, stmt)
                except PartitionError as e:
                    raise ExecError(str(e)) from None
                self.node._log({"op": "partition_parent",
                                "table": td.name,
                                "method": stmt.partition_by[0],
                                "key": stmt.partition_by[1]}, sync=True)
            return Result("CREATE TABLE")
        if isinstance(stmt, A.CreatePartitionStmt):
            from ..parallel.partition import (PartitionError,
                                              child_tabledef,
                                              partition_bounds)
            try:
                ptd, rec = partition_bounds(self.node.catalog, stmt)
            except PartitionError as e:
                raise ExecError(str(e)) from None
            child = child_tabledef(ptd, stmt.name)
            self.node.catalog.create_table(child)
            self.node.stores[child.name] = TableStore(child)
            self.node._log({"op": "create_table",
                            "table": child.to_json()}, sync=True)
            self.node.catalog.partitioned[stmt.parent]["parts"].append(
                rec)
            self.node._log({"op": "create_partition",
                            "parent": stmt.parent, "rec": rec},
                           sync=True)
            return Result("CREATE TABLE")
        if isinstance(stmt, A.DropTableStmt):
            if stmt.name in self.node.catalog.tables:
                from .constraints import drop_guards
                drop_guards(self.node.catalog, stmt.name)
            pinfo = self.node.catalog.partitioned.get(stmt.name)
            if pinfo is not None:
                for p in list(pinfo["parts"]):
                    self._exec_stmt(A.DropTableStmt(p["name"], True))
                del self.node.catalog.partitioned[stmt.name]
            else:
                for parent, pi in self.node.catalog.partitioned.items():
                    pi["parts"] = [p for p in pi["parts"]
                                   if p["name"] != stmt.name]
            self.node.catalog.drop_table(stmt.name, stmt.if_exists)
            st = self.node.stores.pop(stmt.name, None)
            if st is not None:
                self.node.cache.invalidate(st)
            self.node._log({"op": "drop_table", "name": stmt.name},
                           sync=True)
            return Result("DROP TABLE")
        if isinstance(stmt, A.CreateSequenceStmt):
            self.node.catalog.create_sequence(sequence_def_from_ast(stmt))
            return Result("CREATE SEQUENCE")
        if isinstance(stmt, A.CreateIndexStmt):
            if stmt.method == "ivfflat":
                try:
                    self.node.stores[stmt.table].build_ann_index(
                        stmt.columns[0],
                        int(stmt.options.get("lists", 0)),
                        str(stmt.options.get("metric", "l2")))
                except ValueError as e:
                    raise ExecError(str(e)) from None
            elif stmt.method == "hnsw":
                try:
                    self.node.stores[stmt.table].build_hnsw_index(
                        stmt.columns[0],
                        int(stmt.options.get("m", 16)),
                        int(stmt.options.get("ef_construction", 64)),
                        str(stmt.options.get("metric", "l2")))
                except ValueError as e:
                    raise ExecError(str(e)) from None
            else:  # btree (the default access method)
                try:
                    for col in stmt.columns:
                        self.node.stores[stmt.table].build_btree_index(col)
                except (ValueError, KeyError) as e:
                    raise ExecError(str(e)) from None
                self.node.catalog.btree_cols.setdefault(
                    stmt.table, set()).update(stmt.columns)
            return Result("CREATE INDEX")
        if isinstance(stmt, A.CreateViewStmt):
            try:
                self.node.catalog.create_view(stmt.name, stmt.text,
                                              stmt.or_replace)
            except CatalogError as e:
                raise ExecError(str(e)) from None
            self.node._log({"op": "create_view", "name": stmt.name,
                            "text": stmt.text}, sync=True)
            return Result("CREATE VIEW")
        if isinstance(stmt, A.DropViewStmt):
            try:
                self.node.catalog.drop_view(stmt.name, stmt.if_exists)
            except CatalogError as e:
                raise ExecError(str(e)) from None
            self.node._log({"op": "drop_view", "name": stmt.name}, sync=True)
            return Result("DROP VIEW")
        if isinstance(stmt, A.AlterTableStmt):
            return self._exec_alter(stmt)
        if isinstance(stmt, A.InsertStmt):
            return self._exec_insert(stmt)
        if isinstance(stmt, A.DeleteStmt):
            return self._exec_delete(stmt)
        if isinstance(stmt, A.UpdateStmt):
            return self._exec_update(stmt)
        if isinstance(stmt, A.CopyStmt):
            return self._exec_copy(stmt)
        if isinstance(stmt, A.TxnStmt):
            return self._exec_txn(stmt)
        if isinstance(stmt, A.ExplainStmt):
            return self._exec_explain(stmt)
        if isinstance(stmt, A.SetStmt):
            self.node.gucs[stmt.name] = str(stmt.value)
            return Result("SET")
        if isinstance(stmt, A.ShowStmt):
            v = self.node.gucs.get(stmt.name, "")
            return Result("SHOW", names=[stmt.name], rows=[(v,)])
        if isinstance(stmt, A.VacuumStmt):
            self.node.checkpoint()
            return Result("VACUUM")
        if isinstance(stmt, A.AnalyzeStmt):
            from ..parallel.statistics import analyze_store
            names = [stmt.table] if stmt.table else \
                list(self.node.stores)
            for name in names:
                st = self.node.stores.get(name)
                if st is None:
                    raise ExecError(f"table {name!r} does not exist")
                self.node.catalog.stats[name] = analyze_store(st)
            return Result("ANALYZE")
        if isinstance(stmt, A.BarrierStmt):
            self.node.checkpoint()
            return Result("BARRIER")
        if isinstance(stmt, A.CreateNodeGroupStmt):
            name_to_idx = {nd.name: nd.index
                           for nd in self.node.catalog.datanodes()}
            members = []
            for m in stmt.members:
                if m not in name_to_idx:
                    raise ExecError(f"unknown datanode {m!r}")
                members.append(name_to_idx[m])
            try:
                self.node.catalog.create_node_group(stmt.name, members)
            except CatalogError as e:
                raise ExecError(str(e)) from None
            # WAL-logged: recovery must rebuild the group BEFORE
            # replaying dependent CREATE TABLE records (the catalog
            # validates TO GROUP at create time)
            self.node._log({"op": "create_node_group",
                            "name": stmt.name, "members": members},
                           sync=True)
            return Result("CREATE NODE GROUP")
        if isinstance(stmt, A.TruncateStmt):
            return self._exec_truncate(stmt)
        if isinstance(stmt, A.SavepointStmt):
            return self._exec_savepoint(stmt)
        if isinstance(stmt, A.MergeStmt):
            return self._exec_merge(stmt)
        raise ExecError(f"unsupported statement {type(stmt).__name__}")

    # ---- TRUNCATE (reference: ExecuteTruncate, commands/tablecmds.c:
    # non-MVCC relfilenode swap; like PG, refused when the table is
    # referenced by a foreign key) ----
    def _exec_truncate(self, stmt: A.TruncateStmt) -> Result:
        cat = self.node.catalog
        cat.table(stmt.table)                     # existence check
        if self.txn is not None:
            raise ExecError("TRUNCATE cannot run inside a transaction "
                            "block (non-MVCC bulk clear)")
        from .constraints import drop_guards
        drop_guards(cat, stmt.table, action="truncate")
        if self.node.active_txns:
            raise ExecError(
                "cannot truncate: in-flight transactions hold row "
                "spans")
        names = [stmt.table]
        if stmt.table in cat.partitioned:
            names += [p["name"]
                      for p in cat.partitioned[stmt.table]["parts"]]
        for nm in names:
            st = self.node.stores[nm]
            st.truncate()
            self.node.cache.invalidate(st)
            self.node._log({"op": "truncate", "table": nm}, sync=True)
        return Result("TRUNCATE TABLE")

    # ---- SAVEPOINT / ROLLBACK TO / RELEASE (reference: subxact
    # machinery, access/transam/xact.c DefineSavepoint /
    # RollbackToSavepoint) ----
    def _exec_savepoint(self, stmt: A.SavepointStmt) -> Result:
        t = self.txn
        if t is None or not t.explicit:
            raise ExecError(f"{stmt.op.replace('_', ' ').upper()} can "
                            "only be used in transaction blocks")
        if stmt.op == "savepoint":
            t.savepoints[stmt.name] = (len(t.insert_spans),
                                       len(t.delete_spans),
                                       len(t.lock_spans), t.wal_ops)
            return Result("SAVEPOINT")
        if stmt.name not in t.savepoints:
            raise ExecError(f"savepoint {stmt.name!r} does not exist")
        if stmt.op == "release":
            # drop the named savepoint and everything after it
            drop = False
            for nm in list(t.savepoints):
                if nm == stmt.name:
                    drop = True
                if drop:
                    del t.savepoints[nm]
            return Result("RELEASE")
        mi, md, ml, keep_wal = t.savepoints[stmt.name]
        for st, spans in t.insert_spans[mi:]:
            st.abort_insert(spans)
        del t.insert_spans[mi:]
        for st, span in t.delete_spans[md:]:
            st.revert_delete([span])
        del t.delete_spans[md:]
        for st, span in t.lock_spans[ml:]:
            st.clear_locks([span])
        del t.lock_spans[ml:]
        self.node._log({"op": "subabort", "txid": t.txid,
                        "keep": keep_wal})
        t.wal_ops = keep_wal
        drop = False
        for nm in list(t.savepoints):
            if drop:
                del t.savepoints[nm]
            if nm == stmt.name:
                drop = True
        # ROLLBACK TO recovers a failed transaction (PG semantics)
        self.txn_aborted = False
        return Result("ROLLBACK")

    # ---- MERGE (reference: executor/execMerge.c ExecMerge) ----
    def _merge_parts(self, stmt: A.MergeStmt):
        """Decompose MERGE set-wise.  ON must be one equality between
        a target and a source column; each WHEN branch becomes one
        engine query + one DML (columnar, not per-row)."""
        cat = (self.node.catalog if hasattr(self, "node")
               else self.cluster.catalog)
        tgt = cat.table(stmt.target)
        cat.table(stmt.source)
        on = stmt.on
        if not (isinstance(on, A.BinOp) and on.op == "="
                and isinstance(on.left, A.ColRef)
                and isinstance(on.right, A.ColRef)):
            raise ExecError("MERGE ON must be a single equality "
                            "tgt.col = src.col")
        sides = {}
        for e in (on.left, on.right):
            if len(e.parts) != 2:
                raise ExecError("MERGE ON columns must be qualified")
            sides[e.parts[0]] = e.parts[1]
        if set(sides) != {stmt.target, stmt.source}:
            raise ExecError("MERGE ON must join target to source")
        return tgt, sides[stmt.target], sides[stmt.source]

    def _exec_merge(self, stmt: A.MergeStmt) -> Result:
        tgt, tkey, skey = self._merge_parts(stmt)
        t, implicit = self._begin_implicit()
        if implicit:
            self.txn = t
        total = 0
        try:
            total = self._merge_steps(stmt, tgt, tkey, skey)
        except Exception:
            if implicit:
                self.txn = None
                self._abort(t)
            raise
        if implicit:
            self.txn = None
            self._commit(t)
        return Result("MERGE", rowcount=total)

    def _merge_steps(self, stmt: A.MergeStmt, tgt, tkey: str,
                     skey: str) -> int:
        total = 0
        join = A.JoinRef("inner", A.TableRef(stmt.target),
                         A.TableRef(stmt.source), stmt.on)
        if stmt.matched_set is not None:
            assigned = {c: e for c, e in stmt.matched_set}
            if tkey in assigned:
                raise ExecError("MERGE may not update the join key")
            items = [A.SelectItem(
                assigned.get(c.name, A.ColRef((stmt.target, c.name))),
                alias=c.name) for c in tgt.columns]
            rows = self._exec_stmt(
                A.SelectStmt(items=items, from_=[join])).rows
            if rows:
                ki = [c.name for c in tgt.columns].index(tkey)
                keys = sorted({r[ki] for r in rows})
                # PG errors only when ONE TARGET row is matched by
                # MULTIPLE SOURCE rows; several target rows matching
                # one source row each update once (execMerge.c)
                from collections import Counter
                scnt = Counter(r[0] for r in self._exec_stmt(
                    A.SelectStmt(
                        items=[A.SelectItem(
                            A.ColRef((stmt.source, skey)), alias="k")],
                        from_=[A.TableRef(stmt.source)])).rows)
                if any(scnt[k] > 1 for k in keys):
                    raise ExecError(
                        "MERGE command cannot affect row a second "
                        "time (duplicate source join keys)")
                self._exec_stmt(A.DeleteStmt(
                    stmt.target, _in_list(stmt.target, tkey, keys)))
                cols = {c.name: [r[i] for r in rows]
                        for i, c in enumerate(tgt.columns)}
                self._merge_insert(tgt, cols, len(rows))
                total += len(rows)
        elif stmt.matched_delete:
            rows = self._exec_stmt(A.SelectStmt(
                items=[A.SelectItem(
                    A.ColRef((stmt.target, tkey)), alias="k")],
                from_=[join], distinct=True)).rows
            if rows:
                keys = sorted({r[0] for r in rows})
                r = self._exec_stmt(A.DeleteStmt(
                    stmt.target, _in_list(stmt.target, tkey, keys)))
                total += r.rowcount
        if stmt.insert_values is not None:
            cols = stmt.insert_cols or [c.name for c in tgt.columns]
            if len(cols) != len(stmt.insert_values):
                raise ExecError("MERGE INSERT column count mismatch")
            # anti-join: source rows with no target match
            items = [A.SelectItem(e, alias=cn)
                     for cn, e in zip(cols, stmt.insert_values)]
            sel = A.SelectStmt(
                items=items,
                from_=[A.JoinRef("left", A.TableRef(stmt.source),
                                 A.TableRef(stmt.target), stmt.on)],
                where=A.NullTest(A.ColRef((stmt.target, tkey)), True))
            rows = self._exec_stmt(sel).rows
            if rows:
                coldata = {cn: [r[i] for r in rows]
                           for i, cn in enumerate(cols)}
                self._merge_insert(tgt, coldata, len(rows),
                                   cols=cols)
                total += len(rows)
        return total

    def _merge_insert(self, td, coldata, n, cols=None):
        # partition-aware: route through the same paths INSERT uses
        if td.name in self.node.catalog.partitioned:
            self._insert_partitioned(td.name, coldata, n)
            return
        self._check_partition_bound(td.name, coldata, n)
        self._insert_rows(td, self.node.stores[td.name], coldata, n)

    # ---- ALTER TABLE (reference: tablecmds.c ATExecCmd subset) ----
    @staticmethod
    def _alter_guards(catalog, stmt: A.AlterTableStmt):
        """Shared validation: a dist key, indexed column, or partition
        key cannot be dropped/renamed; returns the TableDef."""
        td = catalog.table(stmt.table)
        part_parent = next(
            (p for p, pi in catalog.partitioned.items()
             if any(pt["name"] == stmt.table for pt in pi["parts"])),
            None)
        if stmt.action in ("drop_column", "rename_column"):
            if stmt.name in td.distribution.dist_cols:
                raise ExecError(
                    f"cannot alter distribution column {stmt.name!r}")
            pkey = (catalog.partitioned.get(stmt.table) or
                    (catalog.partitioned[part_parent]
                     if part_parent else None))
            if pkey is not None and stmt.name == pkey["key"]:
                raise ExecError(
                    f"cannot alter partition key column {stmt.name!r}")
            from .constraints import column_drop_guards
            column_drop_guards(catalog, stmt.table, stmt.name)
            if not td.has_column(stmt.name):
                raise ExecError(f"column {stmt.name!r} does not exist")
            idx_cols = catalog.btree_cols.get(stmt.table, set())
            gidx = catalog.global_indexes.get(stmt.table, {})
            if stmt.name in idx_cols or stmt.name in gidx:
                raise ExecError(
                    f"column {stmt.name!r} is indexed; drop the index "
                    "first")
        if stmt.action == "add_column" and \
                td.has_column(stmt.column.name):
            raise ExecError(
                f"column {stmt.column.name!r} already exists")
        if stmt.action == "rename_column" and \
                td.has_column(stmt.new_name):
            raise ExecError(
                f"column {stmt.new_name!r} already exists")
        if stmt.action == "rename_table":
            if stmt.new_name in catalog.tables:
                raise ExecError(
                    f"table {stmt.new_name!r} already exists")
            if catalog.global_indexes.get(stmt.table):
                raise ExecError("cannot rename a table with global "
                                "indexes; drop them first")
            if part_parent is not None:
                raise ExecError(
                    f"cannot rename partition {stmt.table!r} of "
                    f"table {part_parent!r}")
        return td

    def _exec_alter(self, stmt: A.AlterTableStmt) -> Result:
        cat = self.node.catalog
        if stmt.table in cat.partitioned:
            if stmt.action == "rename_table":
                raise ExecError("renaming a partitioned table is not "
                                "supported")
            # DDL recurses to every partition (reference: ATExecCmd
            # recursing over inheritance children)
            r = self._exec_alter_one(stmt)
            for part in cat.partitioned[stmt.table]["parts"]:
                self._exec_alter_one(
                    dataclasses.replace(stmt, table=part["name"]))
            return r
        return self._exec_alter_one(stmt)

    def _exec_alter_one(self, stmt: A.AlterTableStmt) -> Result:
        cat = self.node.catalog
        td = self._alter_guards(cat, stmt)
        st = self.node.stores[stmt.table]
        if stmt.action == "add_column":
            from ..catalog import types as T
            from ..catalog.schema import ColumnDef
            c = stmt.column
            cd = ColumnDef(c.name,
                           T.type_from_name(c.type_name, c.type_args))
            st.alter_add_column(cd)
        elif stmt.action == "drop_column":
            st.alter_drop_column(stmt.name)
        elif stmt.action == "rename_column":
            st.alter_rename_column(stmt.name, stmt.new_name)
        elif stmt.action == "rename_table":
            cat.tables[stmt.new_name] = cat.tables.pop(stmt.table)
            cat.tables[stmt.new_name].name = stmt.new_name
            self.node.stores[stmt.new_name] = \
                self.node.stores.pop(stmt.table)
            cat.btree_cols.pop(stmt.table, None)
        self.node.cache.invalidate(st)
        cat.stats.pop(stmt.table, None)
        self.node._log({"op": "alter_table", "table": stmt.table,
                        "action": stmt.action,
                        "column": (stmt.column.name, stmt.column.type_name,
                                   list(stmt.column.type_args))
                        if stmt.column else None,
                        "name": stmt.name, "new_name": stmt.new_name},
                       sync=True)
        return Result("ALTER TABLE")

    # ---- SELECT ----
    def _plan_select(self, stmt: A.SelectStmt,
                     apply_masks: bool = True) -> PlannedStmt:
        # generic ad-hoc plan cache (exec/plancache.py; the cluster
        # session's twin): identical statements reuse the PlannedStmt
        # and, through the fused tier's memoization, the compiled
        # program
        from .plancache import get_or_build
        node = self.node
        gen = (getattr(node, "ddl_gen", 0),
               len(node.catalog.tables), len(node.catalog.views),
               tuple(sorted(node.gucs.items())))

        masks = apply_masks and \
            not getattr(self, "_unmasked_reads", False) and \
            node.gucs.get("bypass_datamask", "off") != "on"

        def build():
            bq = Binder(node.catalog,
                        apply_masks=masks).bind_select(stmt)
            return Planner(node.catalog).plan(bq)

        with obs_trace.span("plan") \
                if obs_trace.ENABLED else obs_trace.NULL_SPAN:
            return get_or_build(node, "_plan_cache", stmt,
                                (gen, masks), build)

    def _exec_select(self, stmt: A.SelectStmt,
                     instrument: bool = False):
        """Plain SELECT.  With ``instrument`` (the EXPLAIN ANALYZE
        path) the eager tier runs under an InstrumentedExecutor and
        the return value is ``(Result, executor_or_None, planned)`` —
        per-node actuals ride ``executor.node_stats``."""
        if stmt.for_update:
            res = self._exec_select_for_update(stmt)
            return (res, None, None) if instrument else res
        planned = self._plan_select(stmt)
        t, implicit = self._begin_implicit()
        batch = None
        exe = None

        def prerun_init_plans():
            # init plans must run first so their scalars reach the
            # chunk/slab/partition passes (the in-memory path does
            # this in Executor.run); returns (params, stripped plan)
            if not planned.init_plans:
                return {}, planned
            ctx0 = ExecContext(self.node.stores, t.snapshot_ts,
                               t.txid, self.node.cache)
            ex0 = Executor(ctx0)
            for ip in planned.init_plans:
                b0 = ex0.exec_node(ip.plan)
                from .executor import scalar_from_batch
                ctx0.params[ip.name] = (scalar_from_batch(b0),
                                        ip.type)
            return dict(ctx0.params), PlannedStmt(
                planned.plan, [], planned.output_names)

        raw_morsel = self.node.gucs.get("morsel", "auto")
        if raw_morsel != "off" and not instrument:
            # out-of-core streaming tier: the dominant scan streams
            # through fixed-shape device chunk windows (exec/morsel.py)
            from .morsel import MorselDriver, default_chunk_rows
            raw_cr = self.node.gucs.get("morsel_chunk_rows", "")
            cr = int(raw_cr) if raw_cr.isdigit() and int(raw_cr) > 0 \
                else default_chunk_rows()
            from .share import enabled as sharing_enabled
            drv_m = MorselDriver(self.node.stores, self.node.cache,
                                 t.snapshot_ts, t.txid, chunk_rows=cr,
                                 forced=(raw_morsel == "on"),
                                 share=sharing_enabled(self.node.gucs))
            params_m, planned_m = prerun_init_plans()
            drv_m.params = dict(params_m)
            batch = drv_m.try_run(planned_m)
        raw_budget = self.node.gucs.get("work_mem_rows", "")
        if batch is None and raw_budget.isdigit() \
                and int(raw_budget) > 0:
            # beyond-HBM tier: multi-pass partitioned execution when a
            # scanned table exceeds the staging budget (exec/spill.py)
            from .spill import SpillDriver
            drv = SpillDriver(self.node.stores, self.node.cache,
                              t.snapshot_ts, t.txid, int(raw_budget))
            params_s, planned_spill = prerun_init_plans()
            drv.params = dict(params_s)
            batch = drv.try_run(planned_spill)
        if batch is None:
            ctx = ExecContext(self.node.stores, t.snapshot_ts, t.txid,
                              self.node.cache)
            with obs_trace.span("execute", tier="single") \
                    if obs_trace.ENABLED else obs_trace.NULL_SPAN:
                if instrument:
                    from .executor import InstrumentedExecutor
                    exe = InstrumentedExecutor(ctx)
                    batch = exe.run(planned)
                else:
                    batch = Executor(ctx).run(planned)
        names, rows = materialize(batch, planned.output_names)
        qt = obs_trace.current_trace() if obs_trace.ENABLED else None
        if qt is not None:
            qt.rows = len(rows)
        res = Result("SELECT", names=names, rows=rows,
                     rowcount=len(rows))
        if instrument:
            return res, exe, planned
        return res

    # ---- DML ----
    def _exec_insert(self, stmt: A.InsertStmt) -> Result:
        td = self.node.catalog.table(stmt.table)
        st = self.node.stores[stmt.table]
        cols = stmt.columns or td.column_names
        if stmt.select is not None:
            planned = self._plan_select(stmt.select)
            t0, _ = self._begin_implicit()
            ctx = ExecContext(self.node.stores, t0.snapshot_ts, t0.txid,
                              self.node.cache)
            batch = Executor(ctx).run(planned)
            _, rows = materialize(batch, planned.output_names)
        else:
            rows = []
            for vr in stmt.values:
                row = []
                for v in vr:
                    if isinstance(v, A.Const):
                        row.append(v.value)
                    elif isinstance(v, A.TypedConst) and v.type_name == "date":
                        row.append(v.value)
                    elif isinstance(v, A.UnaryOp) and v.op == "-" \
                            and isinstance(v.arg, A.Const):
                        row.append(-float(v.arg.value)
                                   if "." in str(v.arg.value)
                                   else -int(v.arg.value))
                    else:
                        raise ExecError("INSERT values must be literals")
                rows.append(row)
        if not rows:
            return Result("INSERT", rowcount=0)
        if len(cols) != len(rows[0]):
            raise ExecError("INSERT column count mismatch")
        coldata = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
        missing = [c for c in td.column_names if c not in coldata]
        if missing:
            raise ExecError(f"INSERT missing columns {missing} "
                            "(defaults unsupported)")
        if stmt.table in self.node.catalog.partitioned:
            return self._insert_partitioned(stmt.table, coldata,
                                            len(rows))
        self._check_partition_bound(stmt.table, coldata, len(rows))
        return Result("INSERT",
                      rowcount=self._insert_rows(td, st, coldata, len(rows)))

    def _check_partition_bound(self, table: str, coldata: dict, n: int):
        from ..parallel.partition import (PartitionError,
                                          check_child_bounds)
        try:
            check_child_bounds(self.node.catalog, table, coldata, n)
        except PartitionError as e:
            raise ExecError(str(e)) from None

    def _insert_partitioned(self, parent: str, coldata: dict,
                            n: int) -> Result:
        """Route inserted rows to their partitions, one transaction
        (reference: ExecFindPartition per row, here batched)."""
        from ..parallel.partition import PartitionError, split_insert
        t, implicit = self._begin_implicit()
        if implicit:
            self.txn = t
        total = 0
        try:
            for child, sub, cn in split_insert(self.node.catalog,
                                               parent, coldata, n):
                ctd = self.node.catalog.table(child)
                total += self._insert_rows(ctd, self.node.stores[child],
                                           sub, cn)
        except PartitionError as e:
            if implicit:
                self.txn = None
                self._abort(t)
            raise ExecError(str(e)) from None
        except Exception:
            if implicit:
                self.txn = None
                self._abort(t)
            raise
        if implicit:
            self.txn = None
            self._commit(t)
        return Result("INSERT", rowcount=total)

    def _partition_dml_fanout(self, stmt) -> Result:
        """UPDATE/DELETE on a partitioned parent: fan out per surviving
        child in one transaction; updating the partition key is
        rejected (reference: pre-v11 behavior, no row movement)."""
        from ..parallel.partition import prune_partitions
        cat = self.node.catalog
        pinfo = cat.partitioned[stmt.table]
        key_t = cat.table(stmt.table).column(pinfo["key"]).type
        is_update = isinstance(stmt, A.UpdateStmt)
        if is_update and any(col == pinfo["key"]
                             for col, _ in stmt.assignments):
            raise ExecError("updating the partition key is not "
                            "supported (no row movement)")
        names = prune_partitions(pinfo, key_t, stmt.where, stmt.table)
        t, implicit = self._begin_implicit()
        if implicit:
            self.txn = t
        total = 0
        try:
            from ..parallel.partition import rewrite_parent_refs
            for nm in names:
                w = rewrite_parent_refs(stmt.where, stmt.table, nm)
                if is_update:
                    asg = [(cn, rewrite_parent_refs(e, stmt.table, nm))
                           for cn, e in stmt.assignments]
                    child_stmt = A.UpdateStmt(nm, asg, w)
                else:
                    child_stmt = A.DeleteStmt(nm, w)
                total += self._exec_stmt(child_stmt).rowcount
        except Exception:
            if implicit:
                self.txn = None
                self._abort(t)
            raise
        if implicit:
            self.txn = None
            self._commit(t)
        return Result("UPDATE" if is_update else "DELETE",
                      rowcount=total)

    def _run_check_query(self, sel: A.SelectStmt, t) -> list:
        """Constraint-validation SELECT inside txn `t` (sees its own
        uncommitted rows through MVCC own-txid visibility)."""
        planned = self._plan_select(sel, apply_masks=False)
        ctx = ExecContext(self.node.stores, t.snapshot_ts, t.txid,
                          self.node.cache)
        batch = Executor(ctx).run(planned)
        _, rows = materialize(batch, planned.output_names)
        return rows

    def _validate_write(self, table: str, t, kind: str = "insert"):
        from .constraints import (tables_needing_validation,
                                  validate_after_write)
        if not tables_needing_validation(self.node.catalog, table,
                                         kind):
            return
        validate_after_write(
            lambda sel: self._run_check_query(sel, t),
            self.node.catalog, table, kind)

    def _insert_rows(self, td: TableDef, st: TableStore,
                     coldata: dict, n: int,
                     fire_triggers: bool = True) -> int:
        from .constraints import check_not_null
        from .triggers import has_triggers
        check_not_null(td, coldata, n)
        t, implicit = self._begin_implicit()
        self._track_write(t)
        trig = fire_triggers and has_triggers(self.node.catalog,
                                              td.name, "insert")
        if trig:
            colnames = list(coldata)
            new_rows = [tuple(coldata[cn][i] for cn in colnames)
                        for i in range(n)]
            try:
                self._fire_triggers(t, implicit, td.name, "before",
                                    "insert", new_rows, None, colnames)
            except Exception:
                if implicit:
                    self._abort(t)
                raise
        clean, masks = {}, {}
        for c, vals in coldata.items():
            cv, m = st.split_nulls(c, vals)
            clean[c] = cv
            if m is not None:
                masks[c] = m
        enc = {c: st.encode_column(c, vals) for c, vals in clean.items()}
        loc = Locator(self.node.catalog)
        raw_for_route = {c: np.asanyarray(clean[c])
                         for c in td.distribution.dist_cols} \
            if td.distribution.dist_type == DistType.SHARD else {}
        sid = loc.shard_ids_for_rows(td, raw_for_route) \
            if raw_for_route else None
        rec = {"op": "insert", "table": td.name, "n": n,
               "txid": t.txid,
               "columns": {c: (_text_log_array(v)
                               if td.column(c).type.kind
                               == TypeKind.TEXT else
                               np.asarray(enc[c]))
                           for c, v in clean.items()}}
        if masks:
            rec["nulls"] = masks
        self.node._log(rec)
        spans = st.insert(enc, n, t.txid, shardids=sid,
                          nulls=masks or None)
        t.insert_spans.append((st, spans))
        t.wal_ops += 1
        try:
            self._validate_write(td.name, t)
            if trig:
                self._fire_triggers(t, implicit, td.name, "after",
                                    "insert", new_rows, None, colnames)
        except Exception:
            if implicit:
                self._abort(t)
            raise
        if implicit:
            self._commit(t)
        return n

    def _old_rows(self, table: str, where, t) -> list:
        """Materialize the pre-image rows a DELETE/UPDATE will touch
        (trigger OLD.*), inside txn t."""
        td = self.node.catalog.table(table)
        sel = A.SelectStmt(
            items=[A.SelectItem(A.ColRef((cn,)), alias=cn)
                   for cn in td.column_names],
            from_=[A.TableRef(table)], where=where)
        return self._run_check_query(sel, t)

    def _exec_delete(self, stmt: A.DeleteStmt,
                     fire_triggers: bool = True) -> Result:
        if stmt.table in self.node.catalog.partitioned:
            return self._partition_dml_fanout(stmt)
        td = self.node.catalog.table(stmt.table)
        st = self.node.stores[stmt.table]
        t, implicit = self._begin_implicit()
        self._track_write(t)
        binder = Binder(self.node.catalog)
        quals = []
        if stmt.where is not None:
            sel = A.SelectStmt(items=[A.SelectItem(A.Star())],
                               from_=[A.TableRef(stmt.table)],
                               where=stmt.where)
            bq = binder.bind_select(sel)
            quals = bq.where
        from .triggers import has_triggers
        trig = fire_triggers and has_triggers(self.node.catalog,
                                              td.name, "delete")
        n_deleted = 0
        try:
            old_rows = None
            if trig:
                old_rows = self._old_rows(stmt.table, stmt.where, t)
                self._fire_triggers(t, implicit, td.name, "before",
                                    "delete", None, old_rows,
                                    td.column_names)
            for span, ci, mask in self._mark_with_wait(
                    st, stmt.table, quals, t, lock_only=False):
                t.delete_spans.append((st, span))
                t.wal_ops += 1
                self.node._log({"op": "delete", "table": td.name,
                                "chunk": ci, "mask": mask,
                                "txid": t.txid})
                n_deleted += int(mask.sum())
            if n_deleted:
                self._validate_write(td.name, t, kind="delete")
            if trig and old_rows and n_deleted:
                self._fire_triggers(t, implicit, td.name, "after",
                                    "delete", None, old_rows,
                                    td.column_names)
        except Exception:
            if implicit:
                self._abort(t)
            raise
        if implicit:
            self._commit(t)
        return Result("DELETE", rowcount=n_deleted)

    def _exec_select_for_update(self, stmt: A.SelectStmt) -> Result:
        """SELECT ... FOR UPDATE [NOWAIT]: lock matching rows first
        (blocking on in-progress writers), then read under the same
        snapshot — locked rows cannot change until txn end (reference:
        LockRows on top of the scan, nodeLockRows.c).  Restricted to a
        single plain table, as aggregation/joins destroy row identity
        (PG rejects FOR UPDATE with aggregates too)."""
        if (len(stmt.from_) != 1
                or not isinstance(stmt.from_[0], A.TableRef)
                or stmt.group_by or stmt.group_sets or stmt.setop
                or stmt.distinct or stmt.ctes or stmt.having):
            raise ExecError(
                "FOR UPDATE is only supported on a single-table "
                "SELECT without aggregation/set operations")
        table = stmt.from_[0].name
        st = self.node.stores.get(table)
        if st is None:
            raise ExecError(f"table {table!r} does not exist")
        quals = []
        if stmt.where is not None:
            bq = Binder(self.node.catalog).bind_select(
                A.SelectStmt(items=[A.SelectItem(A.Star())],
                             from_=[A.TableRef(table)],
                             where=stmt.where))
            quals = bq.where
        t, implicit = self._begin_implicit()
        if implicit:
            self.txn = t
        self._track_write(t)
        try:
            for span, _ci, _mask in self._mark_with_wait(
                    st, table, quals, t, lock_only=True,
                    nowait=stmt.for_update == "nowait"):
                t.lock_spans.append((st, span))
            r = self._exec_select(
                dataclasses.replace(stmt, for_update=None))
        except Exception:
            if implicit:
                self.txn = None
                self._abort(t)
            raise
        if implicit:
            self.txn = None
            self._commit(t)
        return r

    def _target_masks(self, st, table: str, quals: list, t) -> list:
        from .expr_compile import compile_pred, host_chunk_env
        out = []
        for ci, ch in st.scan_chunks():
            mask = st.visible_mask(ch, t.snapshot_ts, t.txid)
            if quals:
                env, nullable = host_chunk_env(table, ch)
                dicts = {f"{table}.{k}": d
                         for k, d in st.dicts.items()}
                for q in quals:
                    mask = mask & np.asarray(
                        compile_pred(q, dicts, nullable)(env))
            if mask.any():
                out.append((ci, mask))
        return out

    def _mark_with_wait(self, st, table: str, quals: list, t,
                        lock_only: bool, nowait: bool = False) -> list:
        """Statement-atomic row marking with lock waits (the
        single-node twin of DataNode.delete_where/lock_where;
        reference: heap_delete / heap_lock_tuple blocking on the
        updater xid then re-checking)."""
        from ..storage.lockmgr import LockNotAvailable
        from ..storage.store import (SerializationConflict,
                                     WriteConflict)
        node = self.node
        while True:
            targets = self._target_masks(st, table, quals, t)
            done = []
            try:
                for ci, mask in targets:
                    span = st.lock_rows(ci, mask, t.txid) if lock_only \
                        else st.mark_delete(ci, mask, t.txid)
                    done.append((span, ci, mask))
            except WriteConflict as e:
                if lock_only:
                    st.clear_locks([sp for sp, _c, _m in done])
                else:
                    st.revert_delete([sp for sp, _c, _m in done])
                if nowait:
                    raise LockNotAvailable(
                        "could not obtain lock on row (held by txn "
                        f"{e.holder})") from None
                v = node.lockmgr.verdict(e.holder)
                if v is None:
                    v = node.lockmgr.wait_for(e.holder, t.txid,
                                              node.lock_timeout)
                if v == "committed":
                    raise SerializationConflict(
                        "could not serialize access due to concurrent "
                        f"update (txn {e.holder} committed first)") \
                        from None
                continue
            return done

    def _exec_update(self, stmt: A.UpdateStmt) -> Result:
        # MVCC update = delete + insert of new row versions (the reference
        # heap does the same at tuple level)
        if stmt.table in self.node.catalog.partitioned:
            return self._partition_dml_fanout(stmt)
        td = self.node.catalog.table(stmt.table)
        sel_items = []
        assigned = {c: e for c, e in stmt.assignments}
        for c in td.columns:
            src = assigned.get(c.name, A.ColRef((c.name,)))
            sel_items.append(A.SelectItem(src, alias=c.name))
        sel = A.SelectStmt(items=sel_items, from_=[A.TableRef(stmt.table)],
                           where=stmt.where)
        # UPDATE composes a delete + insert and must be ONE transaction:
        # install the implicit txn as the session txn so the nested
        # statements join it instead of drawing (and committing) their own
        t, implicit = self._begin_implicit()
        if implicit:
            self.txn = t
        try:
            # row locks first: concurrent updaters queue instead of
            # racing the read-write window (reference: heap_update's
            # tuple lock; see the cluster session's twin)
            lock_quals = []
            if stmt.where is not None:
                lock_quals = Binder(self.node.catalog).bind_select(
                    A.SelectStmt(items=[A.SelectItem(A.Star())],
                                 from_=[A.TableRef(stmt.table)],
                                 where=stmt.where)).where
            st_lock = self.node.stores[stmt.table]
            for span, _ci, _m in self._mark_with_wait(
                    st_lock, stmt.table, lock_quals, t, lock_only=True):
                t.lock_spans.append((st_lock, span))
            from .triggers import has_triggers
            trig = has_triggers(self.node.catalog, td.name, "update")
            if trig:
                # OLD images ride the same scan as the NEW values so
                # the two row sets stay aligned row-for-row
                sel = dataclasses.replace(sel, items=list(sel.items) + [
                    A.SelectItem(A.ColRef((c.name,)),
                                 alias="__old__" + c.name)
                    for c in td.columns])
            planned = self._plan_select(sel, apply_masks=False)
            ctx = ExecContext(self.node.stores, t.snapshot_ts, t.txid,
                              self.node.cache)
            batch = Executor(ctx).run(planned)
            names, rows = materialize(batch, planned.output_names)
            old_rows = None
            if trig:
                ncol = len(td.columns)
                old_rows = [r[ncol:] for r in rows]
                rows = [r[:ncol] for r in rows]
                names = names[:ncol]
                self._fire_triggers(t, implicit, td.name, "before",
                                    "update", rows, old_rows, names)
            self._exec_delete(A.DeleteStmt(stmt.table, stmt.where),
                              fire_triggers=False)
            if rows:
                coldata = {c: [r[i] for r in rows]
                           for i, c in enumerate(names)}
                self._insert_rows(td, self.node.stores[stmt.table],
                                  coldata, len(rows),
                                  fire_triggers=False)
            if trig:
                self._fire_triggers(t, implicit, td.name, "after",
                                    "update", rows, old_rows, names)
        except Exception:
            if implicit:
                self.txn = None
                self._abort(t)
            raise
        if implicit:
            self.txn = None
            self._commit(t)
        return Result("UPDATE", rowcount=len(rows))

    # ---- COPY ----
    def _exec_copy(self, stmt: A.CopyStmt) -> Result:
        td = self.node.catalog.table(stmt.table)
        st = self.node.stores[stmt.table]
        delim = str(stmt.options.get("delimiter", "|"))
        cols = stmt.columns or td.column_names
        if stmt.direction == "to":
            rows = self._exec_select(copy_to_select(stmt.table,
                                                    cols)).rows
            n = copy_rows_to_file(stmt.filename, rows, delim)
            return Result("COPY", rowcount=n)
        from ..storage.loader import load_tbl
        coldata = load_tbl(stmt.filename, td, cols, delim)
        n = len(next(iter(coldata.values())))
        if stmt.table in self.node.catalog.partitioned:
            r = self._insert_partitioned(stmt.table, coldata, n)
            return Result("COPY", rowcount=r.rowcount)
        self._check_partition_bound(stmt.table, coldata, n)
        return Result("COPY", rowcount=self._insert_rows(td, st, coldata, n))

    # ---- txn / explain ----
    def _exec_txn(self, stmt: A.TxnStmt) -> Result:
        if stmt.op == "begin":
            if self.txn is None:
                self.txn = TxnState(self.node.gts.next_txid(),
                                    self.node.gts.next_gts())
                self.txn.explicit = True
                self.txn_aborted = False
            return Result("BEGIN")
        if stmt.op == "commit":
            if self.txn is not None:
                if self.txn_aborted:
                    # COMMIT of an aborted txn rolls back (PG); abort
                    # already ran at error time unless savepoints kept
                    # the txn alive for a possible ROLLBACK TO
                    if not getattr(self.txn, "rolled_back", False):
                        self._abort(self.txn)
                    self.txn = None
                    self.txn_aborted = False
                    return Result("ROLLBACK")
                self._commit(self.txn)
                self.txn = None
            return Result("COMMIT")
        if self.txn is not None:
            if not getattr(self.txn, "rolled_back", False):
                self._abort(self.txn)
            self.txn = None
        self.txn_aborted = False
        return Result("ROLLBACK")

    def _exec_explain(self, stmt: A.ExplainStmt) -> Result:
        if not isinstance(stmt.stmt, A.SelectStmt):
            raise ExecError("EXPLAIN supports SELECT only")
        planned = self._plan_select(stmt.stmt)
        text = P.explain(planned.plan)
        if stmt.analyze:
            t0 = time.perf_counter()
            _res, exe, planned2 = self._exec_select(stmt.stmt,
                                                    instrument=True)
            total = (time.perf_counter() - t0) * 1e3
            if exe is not None:
                stats = exe.node_stats

                def ann(nd):
                    st = stats.get(id(nd))
                    if st is None:
                        return ""
                    return (f" (actual rows={st['rows']} "
                            f"time={st['ms']:.2f} ms)")

                text = P.explain(planned2.plan, annotate=ann)
            text += _trace_explain_lines()
            text += f"\nExecution Time: {total:.2f} ms"
        return Result("EXPLAIN", names=["QUERY PLAN"],
                      rows=[(line,) for line in text.split("\n")], text=text)
