"""Device-mesh SQL execution: fragment DAGs as ONE shard_map program.

Reference analog: the FN data plane — producer fragments append tuples to
per-destination FnPages that sender/receiver processes stream over TCP
(src/backend/forward/, postmaster/forwardsend.c:165, execFragment.c:2148
FragmentSendTuple / :2515 FragmentRedistributeData).  On a TPU mesh the
whole apparatus collapses into XLA collectives inside one compiled
program: each logical datanode is a mesh device, table shards are
device-sharded arrays, and

    hash-redistribute  ->  all_to_all over ICI
    broadcast          ->  all_gather
    gather-to-CN       ->  sharded program output, host-assembled
    partial aggregates ->  computed per shard, finalised after exchange

The per-tuple routing loop the reference runs (GetDataRouting,
execFragment.c:2360) is here ONE hash kernel + ONE all_to_all per batch,
and routing matches storage placement exactly: dest = shard_map[hash %
4096] — the same 4096-entry map the locator uses, so redistributed rows
land where colocated base-table shards already live.

Dynamic shapes are handled by the size-class ladder (SURVEY §7.3): join
outputs use a static probe-proportional size and a2a buckets a static
per-destination capacity; the compiled program reports overflow via psum
and the host re-traces one size class up.

TEXT columns cross exchanges as dictionary CODES: staging builds one
UNION dictionary per column across all datanodes (host work proportional
to dictionary size, not rows), so no decode/re-encode ever touches the
row data — the host exchange tier's remaining python cost disappears.

Staged tables are DEVICE-RESIDENT across queries via the shared buffer
pool (storage/bufferpool.py): entries are keyed by the per-DN version
tuple, so a warm repeat stages nothing at all, append-only growth
uploads only the per-DN tail rows (union dictionaries extend in place),
and any other mutation drops the stale arrays lazily.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as PS

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..catalog.schema import NUM_SHARDS
from ..catalog.types import TypeKind
from ..obs import trace as obs_trace
from ..plan import exprs as E
from ..plan import physical as P
from ..plan.distribute import BatchSource, DistPlan, ExchangeRef
from ..storage import codec
from ..storage.batch import next_pow2
from ..utils.dtypes import dev_dtype
from ..utils.hashing import (combine_jax, hash_string, splitmix64_jax)
from . import plancache

# Observability hook (see exec/fused.py EXPORT_HOOK): called as
# EXPORT_HOOK("mesh", fn, flat_args) after each successful program run.
EXPORT_HOOK = None


class MeshUnsupported(Exception):
    """This plan (or cluster) can't run on the device mesh — callers
    fall back to the host-mediated exchange tier."""


class _DictView:
    def __init__(self, values):
        self.values = values


class _MeshStoreView:
    """TableStore facade used by the traced scan: schema + UNION
    dictionaries (codes comparable across every shard)."""

    def __init__(self, td, union_dicts: dict, null_columns: set):
        self.td = td
        self.dicts = {c: _DictView(v) for c, v in union_dicts.items()}
        self.null_columns = set(null_columns)


@dataclasses.dataclass
class _StagedTable:
    arrs: dict          # name -> (ndn*P,) sharded device array
    nrows: object       # (ndn,) int64 sharded — per-shard live row count
    padded: int         # per-shard P (static)
    view: _MeshStoreView
    vkey: tuple


_ALLOWED = (P.SeqScan, P.Filter, P.Project, P.HashJoin, P.Agg, P.Sort,
            P.Limit, P.Window, P.Append, ExchangeRef)


class MeshRunner:
    def __init__(self, cluster):
        from ..parallel.mesh import make_mesh
        if any(not hasattr(dn, "stores")
               and not hasattr(dn, "stage_table")
               for dn in cluster.datanodes):
            raise MeshUnsupported("datanodes are not mesh-stageable")
        if len(jax.devices()) < cluster.ndn:
            raise MeshUnsupported(
                f"{cluster.ndn} datanodes but only "
                f"{len(jax.devices())} devices")
        self.cluster = cluster
        self.mesh = make_mesh(cluster.ndn)
        self.axis = self.mesh.axis_names[0]
        # staged tables live in the SHARED device buffer pool
        # (storage/bufferpool.py): version-keyed residency across
        # queries under one byte budget, with an incremental tail path
        # for append-only growth — this runner only assembles entries
        self._snapshots: dict = {}   # (dn_index, table) -> snapshot
        # staging wall time of the last run on THIS thread: the runner
        # is shared by every concurrent CN session, so a plain instance
        # attribute would let query A's staging time leak into query
        # B's stage_ms/compute_ms split (the serving tier runs many
        # dispatches over one runner) — thread-local scopes the value
        # per dispatch, and a thread that never staged reads 0.0
        self._stage_tls = threading.local()
        # compiled shard_map programs live in the SHARED program cache
        # (exec/plancache.py MESH tier: bounded LRU, global
        # live-executable budget, hit/miss telemetry), keyed per
        # runner; _programs is this runner's build registry — the
        # observability surface (did THIS query compile or reuse?)
        self._programs: dict = {}
        self._ladder: dict = {}

    @property
    def last_stage_ms(self) -> float:
        """Staging wall time of the last run ON THE CALLING THREAD
        (0.0 if this thread never staged) — per-dispatch scoping for
        concurrent sessions sharing the runner."""
        return getattr(self._stage_tls, "ms", 0.0)

    # ------------------------------------------------------------------
    # plan screening
    # ------------------------------------------------------------------
    def _screen(self, dp: DistPlan) -> set:
        """Validate the plan and return the mesh-computable fragment
        set (the split fixpoint runs ONCE per query)."""
        if dp.fqs_node is not None:
            raise MeshUnsupported("FQS plan runs on one node")
        for ex in dp.exchanges:
            if ex.kind not in ("redistribute", "broadcast", "gather",
                              "gather_one"):
                raise MeshUnsupported(f"exchange {ex.kind}")
            for k in ex.keys or []:
                if not isinstance(k, (E.Col, E.TextExpr)):
                    raise MeshUnsupported("non-column exchange key")
        included = self._split_fragments(dp)
        for fi in included:
            self._screen_node(
                next(f for f in dp.fragments if f.index == fi).plan)
        return included

    def _split_fragments(self, dp) -> set:
        """The MESH-COMPUTABLE fragment frontier.  Fragments consuming
        a gather run at the coordinator (a set-op combine, a cross join
        of scalar subqueries): the device program computes everything
        UP TO the gathers and the host finishes from there — hybrid
        execution instead of declining the whole plan (reference: the
        CN always executes the top combine in the FN plane too).  A
        non-gather exchange consumed by a CN-side fragment drags its
        producer off the mesh as well (its output would otherwise only
        exist in device memory), propagated to a fixpoint."""
        gathers = {ex.index for ex in dp.exchanges
                   if ex.kind in ("gather", "gather_one")}
        src_of = {ex.index: ex.source_fragment
                  for ex in dp.exchanges}
        needs = {}
        for frag in dp.fragments:
            if frag.index == dp.top_fragment:
                continue
            needs[frag.index] = {
                n.index for n in self._walk(frag.plan)
                if isinstance(n, ExchangeRef)}
        excluded: set = set()
        changed = True
        while changed:
            changed = False
            for fi, nd in needs.items():
                if fi in excluded:
                    continue
                if any(i in gathers or src_of[i] in excluded
                       for i in nd):
                    excluded.add(fi)
                    changed = True
            for fi, nd in needs.items():
                if fi not in excluded:
                    continue
                for i in nd:
                    if i not in gathers and                             src_of[i] not in excluded:
                        excluded.add(src_of[i])
                        changed = True
        included = {fi for fi in needs if fi not in excluded}
        if not any(src_of[g] in included for g in gathers):
            raise MeshUnsupported(
                "no mesh-computable gather fragment")
        return included

    @staticmethod
    def _walk(node):
        yield node
        for attr in ("child", "left", "right"):
            c = getattr(node, attr, None)
            if c is not None and hasattr(c, "__dataclass_fields__"):
                yield from MeshRunner._walk(c)
        for c in getattr(node, "inputs", None) or []:
            if hasattr(c, "__dataclass_fields__"):
                yield from MeshRunner._walk(c)

    def _screen_node(self, node):
        if not isinstance(node, _ALLOWED):
            raise MeshUnsupported(type(node).__name__)
        if isinstance(node, P.HashJoin):
            if node.kind == "cross":
                raise MeshUnsupported("cross join sizing")
            self._screen_node(node.left)
            self._screen_node(node.right)
            return
        if isinstance(node, P.SeqScan) and node.table.name.startswith(
                "otb_"):
            raise MeshUnsupported("stat view scan")
        for attr in ("child", "left", "right"):
            c = getattr(node, attr, None)
            if isinstance(c, P.PhysNode):
                self._screen_node(c)
        for c in getattr(node, "inputs", None) or []:
            if isinstance(c, P.PhysNode):
                self._screen_node(c)

    # ------------------------------------------------------------------
    # staging: per-DN host chunks -> sharded device arrays + union dicts
    # ------------------------------------------------------------------
    # version-gate: cached["version"] == ver
    def _snapshot(self, dn, name: str) -> dict:
        """One DN's live columns + dictionaries at its current version —
        the shared buffer-pool host snapshot for in-process stores, over
        the wire for TCP datanodes (both version-cached, so an unchanged
        table never re-concatenates or re-ships).  In-process stores
        delegate to POOL.host_snapshot (its own version gate); the wire
        path re-validates the cached snapshot against a fresh
        dn.table_version probe before reuse."""
        if hasattr(dn, "stores"):
            st = dn.stores.get(name)
            if st is None:
                raise MeshUnsupported(f"table {name} missing on dn")
            from ..storage.bufferpool import POOL
            return POOL.host_snapshot(st)
        key = (dn.index, name)
        cached = self._snapshots.get(key)
        ver = dn.table_version(name)
        if ver is None:
            raise MeshUnsupported(f"table {name} missing on "
                                  f"dn{dn.index}")
        if cached is not None and cached["version"] == ver:
            return cached
        snap = dn.stage_table(name)
        if snap is None:
            raise MeshUnsupported(f"table {name} missing on "
                                  f"dn{dn.index}")
        snap["null_columns"] = set(snap["null_columns"])
        self._snapshots[key] = snap
        if len(self._snapshots) > 256:
            self._snapshots.pop(next(iter(self._snapshots)))
        return snap

    def _version_of(self, dn, name: str):
        """Cheap per-DN version probe — staging must NOT materialize a
        snapshot (host_live_columns concatenates the whole table) just
        to discover nothing changed."""
        if hasattr(dn, "stores"):
            st = dn.stores.get(name)
            if st is None:
                raise MeshUnsupported(f"table {name} missing on dn")
            return st.version
        v = dn.table_version(name)
        if v is None:
            raise MeshUnsupported(f"table {name} missing on "
                                  f"dn{dn.index}")
        return v

    def _stage_table(self, name: str) -> _StagedTable:
        from ..storage.bufferpool import POOL, MeshEntry
        vkey = tuple(self._version_of(dn, name)
                     for dn in self.cluster.datanodes)
        ent = POOL.mesh_get(self, name, vkey)
        if ent is not None:
            return ent.staged
        stale = POOL.mesh_peek(self, name)
        if stale is not None:
            entry = self._stage_incremental(name, stale, vkey)
            if entry is not None:
                POOL.mesh_put(self, name, entry)
                return entry.staged
        snaps = [self._snapshot(dn, name)
                 for dn in self.cluster.datanodes]
        vkey = tuple(s["version"] for s in snaps)
        td = self.cluster.catalog.table(name)
        ndn = len(snaps)

        # union dictionaries + per-store code LUTs; the index/LUT state
        # rides along in the pool entry so append-only growth can EXTEND
        # the union (existing codes stay valid) instead of rebuilding
        union_dicts: dict[str, list] = {}
        luts: dict[str, list[np.ndarray]] = {}
        dict_state: dict[str, dict] = {}
        for c in td.columns:
            if c.type.kind != TypeKind.TEXT:
                continue
            values: list[str] = []
            index: dict[str, int] = {}
            col_luts = []
            dn_lens = []
            for s in snaps:
                vals = s["dicts"].get(c.name, [])
                lut = np.empty(max(len(vals), 1), dtype=np.int32)
                for i, v in enumerate(vals):
                    j = index.get(v)
                    if j is None:
                        j = len(values)
                        values.append(v)
                        index[v] = j
                    lut[i] = j
                col_luts.append(lut)
                dn_lens.append(len(vals))
            union_dicts[c.name] = values
            luts[c.name] = col_luts
            dict_state[c.name] = {
                "index": index,
                "luts": [col_luts[i][:dn_lens[i]].copy()
                         for i in range(ndn)],
                "dn_lens": dn_lens}

        null_columns = set()
        for s in snaps:
            null_columns |= s["null_columns"]

        per_dn: list[dict[str, np.ndarray]] = []
        counts = []
        for si, s in enumerate(snaps):
            # shared host-staging source (storage/store.py), with this
            # node's TEXT codes remapped into the union dictionary
            cols = dict(s["cols"])
            counts.append(s["count"])
            for c in td.columns:
                if c.type.kind == TypeKind.TEXT and len(cols[c.name]):
                    cols[c.name] = luts[c.name][si][cols[c.name]]
            for nc in null_columns:
                if f"__null.{nc}" not in cols:
                    cols[f"__null.{nc}"] = np.zeros(counts[-1], bool)
            per_dn.append(cols)

        from ..storage.batch import size_class
        from ..utils.dtypes import stage_cast
        padded = size_class(max(max(counts), 1))
        sh = NamedSharding(self.mesh, PS(self.axis))

        # codec: ONE global descriptor per eligible column, proven
        # against every shard's values at once (storage/codec.py) —
        # codes stay comparable across the mesh, like the TEXT union
        # dictionary.  TEXT code columns stay raw here: mesh union
        # codes live in a different value space than the per-store
        # codes the single-device ladder entry was proven on.
        text_names = {c.name for c in td.columns
                      if c.type.kind == TypeKind.TEXT}
        encs: dict = {}
        enc_aux: dict = {}
        shard_codes: dict = {}
        for colname in per_dn[0]:
            if colname in text_names:
                continue
            parts = [stage_cast(np.asarray(per_dn[si][colname]))
                     for si in range(ndn)]
            r = codec.encode_staged(name, colname,
                                    np.concatenate(parts)
                                    if ndn > 1 else parts[0])
            if r is None:
                continue
            codes, enc, aux = r
            encs[colname] = enc
            enc_aux[colname] = aux
            offs = np.cumsum([0] + [len(p) for p in parts])
            shard_codes[colname] = [codes[offs[i]:offs[i + 1]]
                                    for i in range(ndn)]

        arrs = {}
        nbytes = 0
        for colname, sample in per_dn[0].items():
            sample = stage_cast(sample)
            enc = encs.get(colname)
            if enc is not None:
                buf = np.zeros((ndn, padded), dtype=enc.code_dtype)
                for si in range(ndn):
                    a = shard_codes[colname][si]
                    buf[si, :len(a)] = a
            else:
                buf = np.zeros((ndn, padded, *sample.shape[1:]),
                               dtype=sample.dtype)
                for si in range(ndn):
                    a = per_dn[si][colname]
                    buf[si, :len(a)] = a
            arrs[colname] = jax.device_put(
                buf.reshape(ndn * padded, *buf.shape[2:]), sh)
            nbytes += buf.nbytes
        for colname, enc in encs.items():
            # aux arrays replicate per shard: a (ndn, len) tile sharded
            # on the mesh axis hands every shard its own (len,) copy
            aux = enc_aux[colname]
            rep = np.tile(aux, (ndn, 1))
            arrs[codec.aux_name(colname, enc)] = jax.device_put(
                rep.reshape(ndn * aux.shape[0]), sh)
            nbytes += rep.nbytes
        nrows = jax.device_put(np.asarray(counts, np.int64), sh)
        view = _MeshStoreView(td, union_dicts, null_columns)
        codec.note_staged(view, encs)
        staged = _StagedTable(arrs, nrows, padded, view, vkey)
        POOL.note_upload(nbytes)
        POOL.mesh_put(self, name, MeshEntry(
            name, vkey, staged, list(counts), dict_state,
            set(null_columns), nbytes, encs=encs,
            bytes_logical=codec.logical_nbytes(arrs)))
        return staged

    def _stage_incremental(self, name: str, ent, vkey: tuple):
        """Append-only growth on every DN: keep the resident sharded
        prefix, upload only the per-DN tail rows, extend the union
        dictionaries in place (append-only: resident codes stay valid).
        Returns a fresh pool entry, or None when any DN changed
        non-append-only (or shifted size class) — caller restages."""
        from ..storage.bufferpool import MeshEntry, POOL
        from ..storage.batch import size_class
        from ..utils.dtypes import stage_cast
        dns = self.cluster.datanodes
        if any(not hasattr(dn, "stores") for dn in dns):
            return None     # remote DNs: no mutation log to consult
        stores = []
        for dn in dns:
            st = dn.stores.get(name)
            if st is None:
                return None
            stores.append(st)
        new_counts = []
        for i, st in enumerate(stores):
            if st.version != vkey[i]:
                return None     # raced a writer; take the full path
            if vkey[i] != ent.vkey[i] and not st.appended_only_since(
                    ent.vkey[i], ent.counts[i]):
                return None
            new_counts.append(st.row_count())
        ndn = len(stores)
        P = ent.staged.padded
        if size_class(max(max(new_counts), 1)) != P:
            return None     # size class moved: buffers must grow
        td = self.cluster.catalog.table(name)
        value_cols = [c.name for c in td.columns]
        tails = [st.host_live_columns(value_cols, start=ent.counts[i])
                 for i, st in enumerate(stores)]

        # extend union dictionaries + LUTs, remap tail codes
        view = ent.staged.view
        for c in td.columns:
            if c.type.kind != TypeKind.TEXT:
                continue
            state = ent.dict_state[c.name]
            values = view.dicts[c.name].values
            index = state["index"]
            for i, st in enumerate(stores):
                vals = st.dicts[c.name].values
                lold = state["dn_lens"][i]
                if len(vals) > lold:
                    ext = np.empty(len(vals) - lold, np.int32)
                    for j, v in enumerate(vals[lold:]):
                        code = index.get(v)
                        if code is None:
                            code = len(values)
                            values.append(v)
                            index[v] = code
                        ext[j] = code
                    state["luts"][i] = np.concatenate(
                        [state["luts"][i], ext])
                    state["dn_lens"][i] = len(vals)
                tc = tails[i]
                if len(tc[c.name]):
                    tc[c.name] = state["luts"][i][tc[c.name]]

        # encoded columns: every tail must fit the entry's resident
        # descriptor (the prefix codes can't be rewritten in place).
        # Encode BEFORE any device work — a misfit, or a ladder that
        # moved past this entry, falls back to a full restage.
        for colname, enc in ent.encs.items():
            for i in range(ndn):
                if new_counts[i] <= ent.counts[i]:
                    continue
                codes = codec.encode_tail(
                    name, colname, enc,
                    stage_cast(np.asarray(tails[i][colname])))
                if codes is None:
                    return None
                tails[i][colname] = codes

        new_null = set(ent.null_columns)
        for st in stores:
            new_null |= set(st.null_columns)

        sh = NamedSharding(self.mesh, PS(self.axis))
        arrs = {}
        up = 0
        tail_total = sum(new_counts) - sum(ent.counts)

        def tail_piece(colname, i, length):
            t = tails[i].get(colname)
            if t is None:     # null mask with no NULLs on this DN
                t = np.zeros(length, bool)
            return stage_cast(t)

        aux_cols = codec.enc_names(ent.staged.arrs)
        aux_keys = set(aux_cols.values())
        for colname, devarr in ent.staged.arrs.items():
            if colname in aux_keys:
                continue
            new = devarr
            for i in range(ndn):
                lo, hi = ent.counts[i], new_counts[i]
                if hi <= lo:
                    continue
                t = tail_piece(colname, i, hi - lo)
                new = new.at[i * P + lo:i * P + hi].set(jnp.asarray(t))
                up += t.nbytes
            arrs[colname] = jax.device_put(new, sh)
        for colname, akey in aux_cols.items():
            enc = ent.encs[colname]
            if enc.family != "dict":
                arrs[akey] = ent.staged.arrs[akey]
                continue
            # dictionary tails may have extended the append-only LUT
            # in place: re-upload the fresh replicated copy (same pow2
            # capacity, so no program class changes)
            ah = codec.aux_host(name, colname, enc)
            if ah is None:
                return None
            arrs[akey] = jax.device_put(
                np.tile(ah, (ndn, 1)).reshape(ndn * ah.shape[0]), sh)
            up += ah.nbytes * ndn
        for c in sorted(new_null - ent.null_columns):
            # first NULLs arrived in a tail: the prefix mask is zeros
            buf = jnp.zeros(ndn * P, bool)
            for i in range(ndn):
                lo, hi = ent.counts[i], new_counts[i]
                if hi <= lo:
                    continue
                buf = buf.at[i * P + lo:i * P + hi].set(
                    jnp.asarray(tail_piece(f"__null.{c}", i, hi - lo)))
            arrs[f"__null.{c}"] = jax.device_put(buf, sh)
            view.null_columns.add(c)
        nrows = jax.device_put(np.asarray(new_counts, np.int64), sh)
        staged = _StagedTable(arrs, nrows, P, view, vkey)
        nbytes = sum(int(a.nbytes) for a in arrs.values())
        POOL.note_upload(up, tail_rows=tail_total)
        return MeshEntry(name, vkey, staged, list(new_counts),
                         ent.dict_state, new_null, nbytes,
                         encs=ent.encs,
                         bytes_logical=codec.logical_nbytes(arrs))

    # ------------------------------------------------------------------
    # exchange collectives (inside the traced program)
    # ------------------------------------------------------------------
    def _route_hash(self, b, keys):
        """uint64 routing hash of a local batch — bit-identical to the
        host tier's _route/_eval_host_key + locator placement."""
        hs = []
        for k in keys:
            if isinstance(k, E.TextExpr) or (
                    isinstance(k, E.Col)
                    and b.types[k.name].kind == TypeKind.TEXT):
                col = k.col if isinstance(k, E.TextExpr) else k
                d = b.dicts.get(col.name, [])
                transform = k.apply if isinstance(k, E.TextExpr) \
                    else (lambda s: s)
                lut = np.asarray(
                    [hash_string(transform(v)) for v in d] or [0],
                    dtype=np.uint64)
                codes = jnp.clip(b.cols[col.name], 0, len(lut) - 1)
                hs.append(jnp.asarray(lut)[codes])
            else:
                nm = b.nulls.get(k.name)
                arr = b.cols[k.name].astype(jnp.int64)
                if nm is not None:
                    # NULL keys coalesce onto one node (host tier rule)
                    arr = jnp.where(nm, 0, arr)
                hs.append(arr.astype(jnp.uint64))
        h = splitmix64_jax(hs[0])
        for x in hs[1:]:
            h = combine_jax(h, x)
        return h

    def _a2a_batch(self, b, keys, mult: int):
        """Pack rows per destination + one all_to_all per column.
        Returns (local redistributed DBatch, overflow scalar).

        The per-destination bucket is sized from the SOURCE batch's
        static padding (not the base table's): `src_pad/ndn * mult`,
        where `mult` is this exchange's ladder value — 1 assumes a
        uniform spread, overflow doubles it, and `next_pow2(src_pad)`
        is an absolute cap at which overflow is impossible (a source
        shard cannot send more rows than it has).  Packing computes
        each row's slot with one cumsum per destination — no argsort —
        and the scatter drops dead rows, so the exchange also compacts."""
        from .executor import DBatch
        ndn = self.cluster.ndn
        if ndn == 1:
            # single-node mesh: routing is the identity; no collective —
            # and no materialization: the consumer fragment keeps
            # composing through the indirection in the same program
            return b, jnp.int64(0)
        b.ensure_all()   # exchange: rows physically move between shards
        src_pad = int(b.valid.shape[0])
        cap = next_pow2(src_pad)
        bucket = min(cap, max(64, next_pow2(-(-src_pad // ndn)) * mult))
        h = self._route_hash(b, keys)
        sid = (h % jnp.uint64(NUM_SHARDS)).astype(jnp.int64)
        smap = jnp.asarray(
            np.asarray(self.cluster.catalog.shard_map, np.int32))
        dest = jnp.where(b.valid, smap[sid].astype(jnp.int32), ndn)

        # slot = rank of this row among live rows bound for the same
        # destination (ndn cumsums, each a cheap scan)
        slot = jnp.zeros(src_pad, jnp.int32)
        for d in range(ndn):
            m = dest == d
            slot = jnp.where(m, jnp.cumsum(m.astype(jnp.int32)) - 1,
                             slot)
        live = dest < ndn
        keep = (slot < bucket) & live
        overflow = jnp.sum((slot >= bucket) & live)
        oob = ndn * bucket
        # dropped rows get distinct out-of-range indices so the scatter
        # stays unique-indexed (mode="drop" discards them)
        pack_idx = jnp.where(keep, dest * bucket + slot,
                             oob + jnp.arange(src_pad, dtype=jnp.int32))

        def a2a(arr):
            buf = jnp.zeros((oob, *arr.shape[1:]), arr.dtype)
            buf = buf.at[pack_idx].set(arr, mode="drop",
                                       unique_indices=True)
            return jax.lax.all_to_all(
                buf.reshape(ndn, bucket, *arr.shape[1:]),
                self.axis, 0, 0).reshape(oob, *arr.shape[1:])

        cols = {n: a2a(a) for n, a in b.cols.items()}
        nulls = {n: a2a(a) for n, a in b.nulls.items()}
        mask = jnp.zeros(oob, jnp.bool_).at[pack_idx].set(
            keep, mode="drop", unique_indices=True)
        new_valid = jax.lax.all_to_all(
            mask.reshape(ndn, bucket), self.axis, 0, 0).reshape(-1)
        return (DBatch(cols, new_valid, dict(b.types), dict(b.dicts),
                       nulls),
                jax.lax.psum(overflow, self.axis))

    def _broadcast_batch(self, b):
        from .executor import DBatch
        if self.cluster.ndn == 1:
            return b     # identity broadcast: keep the indirection
        b.ensure_all()   # exchange: rows replicate to every shard

        def ag(arr):
            return jax.lax.all_gather(arr, self.axis, tiled=True)

        return DBatch({n: ag(a) for n, a in b.cols.items()},
                      ag(b.valid), dict(b.types), dict(b.dicts),
                      {n: ag(a) for n, a in b.nulls.items()})

    # ------------------------------------------------------------------
    @staticmethod
    def _bind(node, ex_batches: dict):
        if isinstance(node, ExchangeRef):
            batch = ex_batches.get(node.index)
            if batch is None:
                raise MeshUnsupported(
                    f"exchange {node.index} not materialized")
            return BatchSource(batch)
        clone = dataclasses.replace(node)
        for attr in ("child", "left", "right"):
            c = getattr(clone, attr, None)
            if isinstance(c, P.PhysNode):
                setattr(clone, attr, MeshRunner._bind(c, ex_batches))
        if getattr(clone, "inputs", None):
            clone.inputs = [MeshRunner._bind(c, ex_batches)
                            for c in clone.inputs]
        return clone

    def run(self, dp: DistPlan, snapshot_ts: int, txid: int,
            params: dict) -> dict:
        """Execute the DN side of `dp` on the mesh; returns a dict of
        {gather exchange index: DBatch} — every CN-bound exchange output,
        host-reachable."""
        from .executor import DBatch, ExecContext, Executor

        included = self._screen(dp)
        tables = set()
        for frag in dp.fragments:
            if frag.index not in included:
                continue
            for nd in self._walk(frag.plan):
                if isinstance(nd, P.SeqScan):
                    tables.add(nd.table.name)
        for t in tables:
            for dn in self.cluster.datanodes:
                if hasattr(dn, "stores") and t not in dn.stores:
                    raise MeshUnsupported(f"table {t} missing on dn")

        for k, (v, _t) in params.items():
            if not isinstance(v, (int, float, str, bool, type(None))):
                raise MeshUnsupported("non-scalar init-plan param")

        t_stage = time.perf_counter()
        staged = {}
        for t in sorted(tables):
            with obs_trace.span("stage", table=t, tier="mesh") as sp:
                staged[t] = self._stage_table(t)
                sp.set(padded=staged[t].padded)
        self._stage_tls.ms = (time.perf_counter() - t_stage) * 1e3
        if not staged:
            raise MeshUnsupported("no mesh-stageable scans")
        base_pad = max((s.padded for s in staged.values()), default=64)
        # ladder values (join factors, exchange bucket multipliers,
        # gather classes) LEARNED on a previous execution of the same
        # plan shape are remembered, so steady state runs the compiled
        # program exactly once — no overflow replay per query
        lkey = self._ladder_key(dp, table_names := sorted(staged),
                                staged, included)
        remembered = self._ladder.get(lkey)
        if remembered is not None:
            factors, mults, gathers = (dict(remembered[0]),
                                       dict(remembered[1]),
                                       dict(remembered[2]))
            for ex in dp.exchanges:
                if ex.kind == "redistribute":
                    mults.setdefault(ex.index, 1)
                elif ex.kind in ("gather", "gather_one"):
                    gathers.setdefault(ex.index, min(base_pad, 1 << 16))
        else:
            mults = {ex.index: 1 for ex in dp.exchanges
                     if ex.kind == "redistribute"}
            # per-gather output size classes: traced fragment outputs
            # are worst-case padded (a partial aggregate's buffer is its
            # input size), but the rows that actually cross to the CN
            # are usually few — start small, compact in-program, grow on
            # overflow (the same ladder joins and redistributes ride)
            gathers = {ex.index: min(base_pad, 1 << 16)
                       for ex in dp.exchanges
                       if ex.kind in ("gather", "gather_one")}
            factors = {}
        for _attempt in range(24):
            try:
                out, meta, over_jids, a2a_over, g_over = self._execute(
                    dp, staged, snapshot_ts, txid, params,
                    dict(factors), dict(mults), dict(gathers),
                    included)
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError) as e:
                raise MeshUnsupported(f"host sync in plan: {e}") from None
            grew = False
            for ei in a2a_over:
                mults[ei] = mults.get(ei, 1) * 2
                grew = True
            for jid in over_jids:
                factors[jid] = factors.get(jid, 1) * 2
                if factors[jid] > 4096:
                    raise MeshUnsupported("join size ladder exhausted")
                grew = True
            for gi in g_over:
                gathers[gi] *= 2
                grew = True
            if not grew:
                self._ladder[lkey] = (dict(factors), dict(mults),
                                      dict(gathers))
                if len(self._ladder) > 256:
                    self._ladder.pop(next(iter(self._ladder)))
                result = {}
                # the gather span times the device→host pull of every
                # CN-bound exchange output — the mesh tier's terminal
                # materialization boundary
                with obs_trace.span("gather", tier="mesh"):
                    for gi, (cols, valid, nulls) in out.items():
                        gmeta = meta[gi]
                        result[gi] = DBatch(
                            {n: jnp.asarray(np.asarray(a))
                             for n, a in cols.items()},
                            jnp.asarray(np.asarray(valid)),
                            dict(gmeta["types"]), dict(gmeta["dicts"]),
                            {n: jnp.asarray(np.asarray(a))
                             for n, a in nulls.items()})
                return result, included
            obs_trace.event("retrace", tier="mesh",
                            joins=len(over_jids),
                            exchanges=len(a2a_over),
                            gathers=len(g_over))
        raise MeshUnsupported("size-class ladder exhausted")

    def warm(self, dp: DistPlan, snapshot_ts: int, params: dict) -> bool:
        """AOT warmup: run the plan once OFF the query path, discarding
        the result (reference has no analog — the reference's planner
        has no multi-second compile to hide).  Going through run()
        warms everything the first real execution needs: table staging,
        the traced+compiled shard_map programs (written to the
        persistent XLA cache and to the jit dispatch caches), AND the
        learned size-class ladder — numeric params are traced inputs,
        so any later binding reuses all of it."""
        try:
            self.run(dp, snapshot_ts, 0, params)
            return True
        except MeshUnsupported:
            return False

    def _ladder_key(self, dp, table_names, staged, included):
        """Identity of a plan shape + data scale, independent of the
        ladder values themselves — the key under which learned join
        factors / bucket multipliers / gather classes persist."""
        try:
            return hash((
                tuple((f.index, self._plan_key(f.plan))
                      for f in dp.fragments
                      if f.index in included),
                tuple((ex.index, ex.kind, tuple(ex.keys or ()),
                       ex.source_fragment,
                       tuple(getattr(ex, "sort_keys", None) or ()),
                       getattr(ex, "limit", None))
                      for ex in dp.exchanges),
                tuple((t, staged[t].padded,
                       tuple(sorted(staged[t].arrs)),
                       codec.codec_classes(staged[t].view))
                      for t in table_names),
            ))
        except TypeError:
            raise MeshUnsupported("unhashable plan content") from None

    @staticmethod
    def _compact_local(b, gsz: int):
        """Inside the traced program: compress a fragment's output to
        its live prefix in a (static) gather-class buffer of gsz rows
        per shard.  Returns (cols, valid, nulls, overflowed?) — only
        these gsz rows cross device->host at the CN gather, instead of
        the worst-case padded buffer (at SF1 that was ~0.5 GB/query).
        Gather formulation: output slot j takes the input position
        where the live count first reaches j+1."""
        padded = int(b.valid.shape[0])
        csum = jnp.cumsum(b.valid.astype(jnp.int64))
        n_live = csum[-1]
        idx = jnp.clip(
            jnp.searchsorted(csum, jnp.arange(1, gsz + 1)), 0,
            padded - 1)
        valid = jnp.arange(gsz) < n_live
        over = (n_live > gsz).astype(jnp.int64)
        # indirection-aware: gather_rows composes the compaction index
        # straight through any join indirection, so a gather fragment
        # ending in a join chain ships gsz rows WITHOUT ever
        # materializing the full-width join output buffer
        cols, nulls = b.gather_rows(idx)
        return (cols, valid, nulls, over)

    @staticmethod
    def _topk_spec(ob, ex):
        """(key names, descs, limit) when this gather can cut to a
        per-shard top-k INSIDE the program — sort keys are plain
        non-TEXT columns without null masks (the common
        ORDER BY agg/col LIMIT n tail, e.g. TPC-H Q3/Q10/Q18).
        None = ship the full compacted gather (always correct)."""
        if not ex.sort_keys or not ex.limit:
            return None
        names, descs = [], []
        for k, desc in ex.sort_keys:
            if not isinstance(k, E.Col) or not ob.has_col(k.name) \
                    or ob.maybe_null(k.name) \
                    or ob.types[k.name].kind == TypeKind.TEXT:
                return None
            names.append(k.name)
            descs.append(bool(desc))
        return names, tuple(descs), int(ex.limit)

    @staticmethod
    def _topk_local(cols, valid, nulls, spec):
        """Sort the compacted gather buffer by the sort keys and keep
        the first `limit` rows (reference: SimpleSort on RemoteSubplan
        — each DN pre-sorts/cuts, the CN merge re-sorts ndn*limit
        rows instead of every group)."""
        from ..ops import kernels as K
        names, descs, limit = spec
        keys = tuple(cols[n] for n in names)
        pnames = sorted(cols)
        nnames = sorted(nulls)
        payload = tuple([cols[n] for n in pnames]
                        + [nulls[n] for n in nnames])
        out, s_valid = K.sort_rows(keys, valid, payload, descs, limit)
        new_cols = {n: out[i] for i, n in enumerate(pnames)}
        new_nulls = {n: out[len(pnames) + i]
                     for i, n in enumerate(nnames)}
        return new_cols, s_valid, new_nulls

    @staticmethod
    def _plan_key(node):
        t = type(node).__name__
        if isinstance(node, ExchangeRef):
            return (t, node.index)
        if isinstance(node, P.SeqScan):
            return (t, node.table.name, node.alias, tuple(node.filters),
                    tuple(node.outputs or ()))
        if isinstance(node, P.HashJoin):
            return (t, node.kind, tuple(node.left_keys),
                    tuple(node.right_keys), tuple(node.residual or ()),
                    MeshRunner._plan_key(node.left),
                    MeshRunner._plan_key(node.right))
        if isinstance(node, P.Filter):
            return (t, tuple(node.quals),
                    MeshRunner._plan_key(node.child))
        if isinstance(node, P.Project):
            return (t, tuple(node.outputs),
                    MeshRunner._plan_key(node.child))
        if isinstance(node, P.Agg):
            return (t, node.mode, tuple(node.group_keys),
                    tuple(node.aggs), MeshRunner._plan_key(node.child))
        if isinstance(node, P.Sort):
            return (t, tuple((k, bool(d)) for k, d in node.keys),
                    node.limit, MeshRunner._plan_key(node.child))
        if isinstance(node, P.Limit):
            return (t, node.count, node.offset,
                    MeshRunner._plan_key(node.child))
        if isinstance(node, P.Window):
            return (t, tuple(node.calls),
                    MeshRunner._plan_key(node.child))
        if isinstance(node, P.Append):
            return (t, tuple(MeshRunner._plan_key(c)
                             for c in node.inputs))
        raise MeshUnsupported(t)

    def _execute(self, dp, staged, snapshot_ts, txid, params, factors,
                 mults, gathers, included):
        from .executor import ExecContext, Executor

        table_names = sorted(staged)
        gather_ex = [ex for ex in dp.exchanges
                     if ex.kind in ("gather", "gather_one")
                     and ex.source_fragment in included]
        if not gather_ex:
            raise MeshUnsupported("no gather exchange")
        gather_idx = [ex.index for ex in gather_ex]

        # canonical program signature: numeric params (lifted literals,
        # bound $n params, scalar-subquery results) are MASKED out of
        # the key and ride as TRACED inputs, so same-shape statements
        # with different literals reuse the compiled shard_map program
        traced_names = tuple(sorted(
            k for k, (v, _t) in params.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)))
        baked = {k: params[k] for k in params if k not in traced_names}
        prog_key = (
            id(self),
            tuple((f.index, self._plan_key(f.plan))
                  for f in dp.fragments
                  if f.index in included),
            tuple((ex.index, ex.kind, tuple(ex.keys or ()),
                   ex.source_fragment,
                   tuple(getattr(ex, "sort_keys", None) or ()),
                   getattr(ex, "limit", None))
                  for ex in dp.exchanges),
            tuple((t, staged[t].padded,
                   tuple(sorted((c, len(d.values)) for c, d in
                         staged[t].view.dicts.items())),
                   # the staged-array namespace: a null column appearing
                   # after DML adds a __null input, which must recompile
                   # (the flat-arg list and in_specs grow with it)
                   tuple(sorted(staged[t].arrs)),
                   # quantized codec classes (storage/codec.py): an enc
                   # family/width/LUT-capacity change alters aux avals,
                   # so the class token must be key-visible
                   codec.codec_classes(staged[t].view))
                  for t in table_names),
            tuple(sorted(factors.items())),
            tuple(sorted(mults.items())),
            tuple(sorted(gathers.items())),
            tuple(sorted((k, v) for k, (v, _t) in baked.items())),
            tuple((k, params[k][1]) for k in traced_names),
        )
        try:
            hash(prog_key)
        except TypeError:
            raise MeshUnsupported("unhashable plan content") from None

        has_join = any(
            isinstance(n, P.HashJoin)
            for f in dp.fragments if f.index in included
            for n in self._walk(f.plan))
        cached = plancache.MESH.get(prog_key)
        if cached is not None:
            fn, meta = cached
            if has_join:
                from .executor import bump_stat
                bump_stat("mesh", "fused_join_hits")
            return self._call_program(fn, meta, gather_idx, staged,
                                      table_names, snapshot_ts, txid,
                                      params)

        meta: dict = {"traced": traced_names}

        def prog(snap, txn, *flat):
            pvals = flat[:len(traced_names)]
            flat = flat[len(traced_names):]
            run_params = dict(baked)
            for name, pv in zip(traced_names, pvals):
                run_params[name] = (pv, params[name][1])
            arrs_by_table = {}
            i = 0
            for t in table_names:
                names = sorted(staged[t].arrs)
                arrs_by_table[t] = (
                    {n: flat[i + j] for j, n in enumerate(names)},
                    flat[i + len(names)][0])
                i += len(names) + 1
            ctx = ExecContext(
                stores={t: staged[t].view for t in table_names},
                snapshot_ts=snap, txid=txn, cache=None,
                params=run_params,
                staged=arrs_by_table,
                join_factors=dict(factors))
            ex_batches: dict = {}
            overflows = []
            meta["ex_order"] = []
            join_reqs = []
            gather_out: dict = {}
            gather_over: list = []
            meta["gi_order"] = []
            for frag in dp.fragments:
                if frag.index not in included:
                    continue
                plan = self._bind(frag.plan, ex_batches)
                exe = Executor(ctx, frag_tag=frag.index)
                exe._traced = True
                b = exe.exec_node(plan)
                join_reqs.extend(exe.join_required)
                for ex in dp.exchanges:
                    if ex.source_fragment != frag.index:
                        continue
                    if ex.kind == "redistribute":
                        rb, over = self._a2a_batch(
                            b, ex.keys, mults.get(ex.index, 1))
                        ex_batches[ex.index] = rb
                        meta["ex_order"].append(ex.index)
                        overflows.append(over)
                    elif ex.kind == "broadcast":
                        ex_batches[ex.index] = self._broadcast_batch(b)
                    else:  # gather / gather_one: program output
                        ob = b
                        if ex.kind == "gather_one":
                            keep1 = jax.lax.axis_index(self.axis) == 0
                            ob = dataclasses.replace(
                                ob, valid=ob.valid & keep1)
                        meta[ex.index] = {"types": ob.types,
                                          "dicts": ob.dicts}
                        cols, valid, nulls, gov = self._compact_local(
                            ob, gathers[ex.index])
                        spec = self._topk_spec(ob, ex)
                        if spec is not None:
                            cols, valid, nulls = self._topk_local(
                                cols, valid, nulls, spec)
                        gather_out[ex.index] = (cols, valid, nulls)
                        meta["gi_order"].append(ex.index)
                        gather_over.append(
                            jax.lax.psum(gov, self.axis))
            missing = [gi for gi in gather_idx if gi not in gather_out]
            if missing:
                raise MeshUnsupported(f"gather {missing} not produced")
            a2a_over = jnp.stack(overflows) if overflows \
                else jnp.zeros(0, jnp.int64)
            meta["jid_order"] = [jid for jid, _r, _c in join_reqs]
            if join_reqs:
                join_over = jnp.stack([
                    jax.lax.psum((req > cap).astype(jnp.int64),
                                 self.axis)
                    for _jid, req, cap in join_reqs])
            else:
                join_over = jnp.zeros(0, jnp.int64)
            g_over = jnp.stack(gather_over) if gather_over \
                else jnp.zeros(0, jnp.int64)
            return (tuple(gather_out[gi] for gi in gather_idx),
                    a2a_over, join_over, g_over)

        in_specs = [PS(), PS()] + [PS()] * len(traced_names)
        for t in table_names:
            in_specs.extend([PS(self.axis)] * (len(staged[t].arrs) + 1))

        kwargs = dict(mesh=self.mesh, in_specs=tuple(in_specs),
                      out_specs=(tuple((PS(self.axis), PS(self.axis),
                                        PS(self.axis))
                                       for _ in gather_idx),
                                 PS(), PS(), PS()))
        try:
            smapped = shard_map(prog, check_vma=False, **kwargs)
        except TypeError:
            try:
                smapped = shard_map(prog, check_rep=False, **kwargs)
            except TypeError:
                smapped = shard_map(prog, **kwargs)
        fn = jax.jit(smapped)
        plancache.MESH.put(prog_key, (fn, meta))
        self._programs[prog_key] = True
        while len(self._programs) > 256:
            self._programs.pop(next(iter(self._programs)))
        return self._call_program(fn, meta, gather_idx, staged,
                                  table_names, snapshot_ts, txid,
                                  params)

    def _call_program(self, fn, meta, gather_idx, staged, table_names,
                      snapshot_ts, txid, params):  # otblint: sync-boundary
        from .executor import stats_tier
        flat_args = [jnp.int64(snapshot_ts), jnp.int64(txid)]
        for k in meta.get("traced", ()):
            v, t = params[k]
            flat_args.append(jnp.asarray(v, dtype=dev_dtype(t)))
        for t in table_names:
            for n in sorted(staged[t].arrs):
                flat_args.append(staged[t].arrs[n])
            flat_args.append(staged[t].nrows)
        t0 = time.perf_counter()
        # the execute span covers the program call and the overflow
        # device_gets — the mesh tier's one legal sync point per call,
        # so the span's wall time includes the device work
        with obs_trace.span("execute", tier="mesh"):
            with stats_tier("mesh"):
                # executor counters inside the trace attribute to the
                # mesh tier (first call of a fresh program traces here)
                outs, a2a_over_vec, join_over, g_over_vec = fn(*flat_args)
            plancache.MESH.record_call(fn, t0)
            if EXPORT_HOOK is not None:
                EXPORT_HOOK("mesh", fn, tuple(flat_args))
            over_vec = np.asarray(jax.device_get(join_over))
            over_jids = sorted({jid for jid, ov in
                                zip(meta.get("jid_order", ()), over_vec)
                                if ov > 0})
            av = np.asarray(jax.device_get(a2a_over_vec))
            a2a_over = sorted({ei for ei, ov in
                               zip(meta.get("ex_order", ()), av)
                               if ov > 0})
            gv = np.asarray(jax.device_get(g_over_vec))
            g_over = sorted({gi for gi, ov in
                             zip(meta.get("gi_order", ()), gv) if ov > 0})
        return (dict(zip(gather_idx, outs)), meta, over_jids,
                a2a_over, g_over)


def mesh_runner_for(cluster) -> Optional[MeshRunner]:
    """Lazily build (and cache) the cluster's mesh runner; None when the
    deployment can't use the device tier."""
    r = getattr(cluster, "_mesh_runner", None)
    if r is not None:
        return r if isinstance(r, MeshRunner) else None
    try:
        runner = MeshRunner(cluster)
    except MeshUnsupported:
        cluster._mesh_runner = False
        return None
    cluster._mesh_runner = runner
    return runner
