"""Cross-query work sharing: shared morsel scans + a GTS-versioned
result cache.

Reference analogs: Postgres' synchronized sequential scans
(src/backend/access/heap/syncscan.c) — concurrent seqscans of one
relation piggyback on a single pass of the buffer ring instead of each
driving its own I/O — and pgpool-II's memcached query cache, which
serves repeated statements from memory but must invalidate by table.
"Accelerating Presto with GPUs" (PAPERS.md) makes the accelerator
version of the argument: interactive-concurrency economics on
device-resident data hinge on amortizing data movement and dispatch
across concurrent queries, not on per-query kernel speed.

Two rungs, both exact (never a stale row, never a snapshot violation):

- **Shared morsel scans** (`ShareHub`): when concurrent streaming
  queries' dominant scans hit the same table at the same store version
  with the same chunk shape, the FIRST one becomes the stream leader
  and every later arrival attaches as a follower.  The leader drives
  ONE chunk stream through the bufferpool's pinned chunk cache and
  fans each staged window into every follower's deque — each follower
  runs its OWN compiled fragment with its OWN snapshot over the shared
  device window (MVCC system columns ride in the chunk, so visibility
  is applied per consumer).  N concurrent analytic queries cost one
  pass of host→device traffic instead of N.  Per-consumer pin
  refcounts (storage/bufferpool.py) keep `check_pin_ledger` sound: a
  follower erroring mid-stream can only release its OWN pins.  A late
  joiner attaches at the current offset and re-reads just its missed
  prefix (warm chunk-cache hits when the column sets match); anything
  incompatible falls back to a private stream — sharing is an
  optimization, never a semantic.

- **GTS-versioned result cache** (`ResultCache`): a CN-side cache
  keyed by (literal-masked signature, literal vector, per-table
  store-version tuple), each entry tagged with the snapshot GTS of the
  query that produced it.  Store versions are process-globally unique
  and bump on every mutation, so the version tuple is an exact
  invalidation key — the same machinery the device buffer pool already
  trusts for residency.  An entry is servable to a read iff (a) every
  referenced table still sits at the entry's captured version and (b)
  the reader's snapshot GTS covers the entry's GTS — a cached result
  tagged GTS=t is never served to a snapshot older than t.  Repeat
  dashboard traffic becomes a sub-millisecond CN memory hit that never
  touches the device.

GUCs: `enable_work_sharing` (default on; env OTB_WORK_SHARING) gates
both rungs; `result_cache_bytes` (env OTB_RESULT_CACHE_BYTES, default
64 MiB) bounds the result cache, LRU-evicted.
"""

from __future__ import annotations

import collections
import itertools
import os

from ..obs import xray as _xray
from ..utils import locks, snapcheck

_LOCK = locks.Lock("exec.share._LOCK")
_STATS: dict = {                    # guarded_by: _LOCK
    "shared_streams": 0,            # leader streams that fed >=1 follower
    "shared_scan_fanin": 0,         # follower attachments (extra consumers)
    "shared_chunks": 0,             # chunk windows delivered to followers
    "late_joins": 0,                # followers that attached mid-stream
    "private_fallbacks": 0,         # expels / incompatibilities -> private
    "result_cache_hits": 0,
    "result_cache_misses": 0,
    "result_cache_invalidations": 0,
    "result_cache_puts": 0,
    "result_cache_evictions": 0,
}

_TOKENS = itertools.count(1)


def new_token() -> tuple:
    """Process-unique consumer token for per-consumer pin accounting."""
    return ("share", next(_TOKENS))


def bump(field: str, n: int = 1):
    with _LOCK:
        _STATS[field] += n


def stats_snapshot() -> dict:
    with _LOCK:
        d = dict(_STATS)
    d["result_cache_bytes"] = RESULT_CACHE.nbytes()
    d["result_cache_entries"] = RESULT_CACHE.entries()
    return d


def stats_rows() -> list:
    """One row for the otb_workshare view."""
    d = stats_snapshot()
    return [(d["shared_streams"], d["shared_scan_fanin"],
             d["shared_chunks"], d["late_joins"],
             d["private_fallbacks"], d["result_cache_hits"],
             d["result_cache_misses"], d["result_cache_invalidations"],
             d["result_cache_puts"], d["result_cache_evictions"],
             d["result_cache_bytes"], d["result_cache_entries"])]


def reset_stats():
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _metrics_samples():
    for k, v in stats_snapshot().items():
        yield (f"otb_workshare_{k}", {}, v)


# ---------------------------------------------------------------------------
# GUCs
# ---------------------------------------------------------------------------

def enabled(gucs: dict = None) -> bool:
    """`enable_work_sharing` GUC -> OTB_WORK_SHARING env -> on."""
    raw = (gucs or {}).get("enable_work_sharing")
    if raw is None:
        raw = os.environ.get("OTB_WORK_SHARING", "on")
    return str(raw).strip().lower() not in ("off", "0", "false", "no")


def cache_budget(gucs: dict = None) -> int:
    """`result_cache_bytes` GUC -> OTB_RESULT_CACHE_BYTES -> 64 MiB."""
    raw = (gucs or {}).get("result_cache_bytes")
    if raw is None:
        raw = os.environ.get("OTB_RESULT_CACHE_BYTES", str(64 << 20))
    try:
        return int(raw)
    except (TypeError, ValueError):
        return 64 << 20


def store_versions(stores: dict) -> tuple:
    """The exact-invalidation version key: every referenced table's
    store at its CURRENT monotonic version, sorted for a canonical
    tuple.  Captured at snapshot allocation — a mutation between
    capture and lookup changes the live tuple, so the entry simply
    stops matching (lazy exact invalidation)."""
    return tuple(sorted((t, st.version) for t, st in stores.items()))


# ---------------------------------------------------------------------------
# rung (b): GTS-versioned result cache
# ---------------------------------------------------------------------------

def _rows_nbytes(names, rows) -> int:
    """Cheap, slightly pessimistic memory estimate (sampled)."""
    base = 256 + 64 * len(names)
    if not rows:
        return base
    sample = rows[:32]
    per = 0
    for r in sample:
        per += 56
        for v in r:
            per += 24 + (len(v) if isinstance(v, (str, bytes)) else 8)
    return base + int(per * (len(rows) / len(sample)))


class ResultCache:
    """(masked signature, literal vector) -> one result, valid at one
    per-table version tuple and servable from one snapshot GTS on."""

    def __init__(self):
        self._lock = locks.Lock("exec.share.ResultCache._lock")
        # (sig, lits) -> [seq, vkey, gts, names, rows, rowcount, nbytes]
        self._map: dict = {}       # guarded_by: _lock
        self._bytes = 0            # guarded_by: _lock
        self._seq = itertools.count()

    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def entries(self) -> int:
        with self._lock:
            return len(self._map)

    def clear(self):
        with self._lock:
            self._map.clear()
            self._bytes = 0

    # snapshot-gate: snapshot_gts >= ent[2]
    # version-gate: ent[1] == vkey
    def lookup(self, sig, lits, vkey: tuple, snapshot_gts: int):
        """(names, rows, rowcount) iff an entry exists whose captured
        version tuple equals the CURRENT `vkey` and whose producing
        snapshot GTS is covered by `snapshot_gts`; else None.  A
        version mismatch drops the entry (exact lazy invalidation); a
        too-old reader leaves it resident for newer snapshots."""
        ident = (sig, tuple(lits))
        with self._lock:
            ent = self._map.get(ident)
            if ent is None:
                pass
            elif ent[1] != vkey:
                self._bytes -= ent[6]
                del self._map[ident]
                ent = None
                bump("result_cache_invalidations")
            elif snapshot_gts < ent[2]:
                ent = None      # snapshot predates the cached result
            if ent is None:
                bump("result_cache_misses")
                return None
            ent[0] = next(self._seq)
            bump("result_cache_hits")
            out = ent[3], list(ent[4]), ent[5]
        if snapcheck.enabled():
            snapcheck.serve("exec.share.ResultCache.lookup",
                            snapshot_gts=snapshot_gts, entry_gts=ent[2],
                            versions=ent[1], expect_versions=vkey)
        return out

    def put(self, key, gts: int, names, rows, rowcount: int = None,
            budget: int = None):
        """`key` = (sig, lits, vkey) — the ONLY admissible components
        (analysis/cardinality.py result-key rule): the masked
        signature, the literal vector, and the per-table store-version
        tuple.  `gts` tags the producing snapshot."""
        sig, lits, vkey = key
        ident = (sig, tuple(lits))
        budget = cache_budget() if budget is None else int(budget)
        rows = tuple(rows)
        nb = _rows_nbytes(names, rows)
        if nb > budget:
            return False
        with self._lock:
            old = self._map.pop(ident, None)
            if old is not None:
                self._bytes -= old[6]
            while self._map and self._bytes + nb > budget:
                victim = min(self._map, key=lambda k: self._map[k][0])
                self._bytes -= self._map.pop(victim)[6]
                bump("result_cache_evictions")
            self._map[ident] = [next(self._seq), tuple(vkey), int(gts),
                                tuple(names), rows,
                                len(rows) if rowcount is None
                                else int(rowcount), nb]
            self._bytes += nb
        bump("result_cache_puts")
        return True

    def invalidate_table(self, table: str) -> int:
        """Eagerly drop every entry whose version key references
        `table` (DROP/TRUNCATE paths reclaim CN memory immediately;
        plain DML is caught lazily by the version-tuple mismatch)."""
        dropped = 0
        with self._lock:
            for ident in [k for k, e in self._map.items()
                          if any(t == table for t, _v in e[1])]:
                self._bytes -= self._map.pop(ident)[6]
                dropped += 1
        if dropped:
            bump("result_cache_invalidations", dropped)
        return dropped


#: process-global cache — module-level ResultCache binding (the
#: analysis/cardinality.py result-key pass keys off this spelling)
RESULT_CACHE = ResultCache()


# ---------------------------------------------------------------------------
# rung (a): shared morsel scan streams
# ---------------------------------------------------------------------------

def _stall_s() -> float:
    try:
        return float(os.environ.get("OTB_SHARE_STALL_S", "30"))
    except ValueError:
        return 30.0


#: leader run-ahead bound: a follower's undelivered backlog never
#: exceeds this many pinned windows (bounds HBM wired by sharing)
MAX_BACKLOG = 4


class SharedStream:
    """One leader-driven chunk stream over a store at a fixed version
    and chunk shape, fanned into follower deques."""

    def __init__(self, key, table: str, version: int, chunk_rows: int,
                 names: frozenset, classes: dict):
        self.key = key
        self.table = table
        self.version = version
        self.chunk_rows = chunk_rows
        self.names = names          # leader's staged names (incl. aux)
        self.classes = classes      # column -> codec class key
        self.cond = locks.Condition(
            name="exec.share.SharedStream.cond")
        # token -> {"deque", "join_lo", "expelled"}; guarded_by: cv
        self.followers: dict = {}
        self.published = 0          # next unpublished lo; guarded_by: cv
        self.done = False           # guarded_by: cv
        self.failed = False         # guarded_by: cv
        self.accepting = True       # guarded_by: cv
        self.fanin = 0              # followers ever; guarded_by: cv

    # -- follower side -------------------------------------------------
    def compatible(self, names: frozenset, classes: dict) -> bool:
        if not names <= self.names:
            return False
        return all(self.classes.get(c) == k for c, k in classes.items())

    def detach(self, token):
        """Drop a follower and release every pin it still holds on
        undelivered windows — its OWN pins only (per-consumer
        refcounts), so the leader and other followers keep theirs."""
        from ..storage.bufferpool import POOL
        with self.cond:
            f = self.followers.get(token)
            if f is None:
                return
            f["expelled"] = True
            while f["deque"]:
                _lo, entry = f["deque"].popleft()
                POOL.unpin_chunk(entry, consumer=token)
            self.cond.notify_all()

    # -- leader side ---------------------------------------------------
    def publish(self, entry, lo: int, hi: int):
        """Fan one staged window into every live follower: pin once
        per consumer (the leader's own pin came from get_chunk), then
        enqueue."""
        from ..storage.bufferpool import POOL
        nfed = 0
        with self.cond:
            for token, f in self.followers.items():
                if f["expelled"]:
                    continue
                POOL.pin_chunk(entry, consumer=token)
                f["deque"].append((lo, entry))
                nfed += 1
            self.published = hi
            self.cond.notify_all()
        if nfed:
            bump("shared_chunks", nfed)

    def throttle(self):
        """Bound leader run-ahead: wait until every live follower's
        backlog is under MAX_BACKLOG; a follower stalled past the
        expel deadline is detached (it falls back to a private
        stream when it notices)."""
        deadline_waits = max(1, int(_stall_s() / 0.25))

        def slow_locked():
            return [t for t, f in self.followers.items()
                    if not f["expelled"]
                    and len(f["deque"]) >= MAX_BACKLOG]

        with self.cond:
            for _ in range(deadline_waits):
                if not slow_locked():
                    return
                with _xray.wait_event("share-backlog"):
                    self.cond.wait(timeout=0.25)
            stuck = slow_locked()
        for token in stuck:
            self.detach(token)
            bump("private_fallbacks")

    def finish(self, failed: bool = False):
        with self.cond:
            self.accepting = False
            self.done = True
            self.failed = failed
            fanin = self.fanin
            self.cond.notify_all()
        if failed:
            # expel everyone: undelivered pins release, followers fall
            # back to private streams
            with self.cond:
                tokens = list(self.followers)
            for token in tokens:
                self.detach(token)
        return fanin


class ShareHub:
    """Registry of in-flight shareable streams, keyed by (store
    identity, store version, chunk shape)."""

    def __init__(self):
        self._lock = locks.Lock("exec.share.ShareHub._lock")
        self._streams: dict = {}   # key -> SharedStream; guarded_by: _lock

    def live_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    # version-gate: store.version
    def attach(self, store, chunk_rows: int, names: frozenset,
               classes: dict):
        """("leader", stream, token) for the first arrival,
        ("follower", stream, token, join_lo) for a compatible later
        one, None when an open stream exists but is incompatible (the
        caller streams privately).  The store version rides in the
        stream key AND on the stream object, so a follower can only
        join a pass over exactly the version its own plan resolved."""
        key = (id(store), store.version, int(chunk_rows))
        token = new_token()
        with self._lock:
            stream = self._streams.get(key)
            if stream is None:
                stream = SharedStream(key, store.td.name, store.version,
                                      int(chunk_rows), names,
                                      dict(classes))
                self._streams[key] = stream
                return "leader", stream, token, 0
        with stream.cond:
            if not stream.accepting \
                    or not stream.compatible(names, classes):
                return None
            join_lo = stream.published
            stream.followers[token] = {
                "deque": collections.deque(),
                "join_lo": join_lo, "expelled": False}
            stream.fanin += 1
        bump("shared_scan_fanin")
        if join_lo > 0:
            bump("late_joins")
        if snapcheck.enabled():
            snapcheck.serve("exec.share.ShareHub.attach",
                            versions=[(stream.table, stream.version)],
                            expect_versions=[(store.td.name,
                                              store.version)])
        return "follower", stream, token, join_lo

    def remove(self, stream: SharedStream):
        with self._lock:
            if self._streams.get(stream.key) is stream:
                del self._streams[stream.key]


#: process-global hub — one stream per (store, version, shape) at a time
HUB = ShareHub()


from ..obs.metrics import REGISTRY as _METRICS  # noqa: E402
_METRICS.register_collector("workshare", _metrics_samples)
