"""Compiled-program subsystem: one cache for every execution tier.

Reference analog: CachedPlanSource (utils/cache/plancache.c) generalized
to the thing that actually costs seconds here — compiled XLA programs.
The round-5 ladder paid 11-12s of XLA compile against <1s of engine
time per cold mesh query, and an unmanaged live-executable population
segfaulted XLA:CPU at a few hundred programs.  Four pieces:

1. ProgramCache — a bounded LRU of live compiled programs, shared by
   the fused tier (exec/fused.py) and the mesh tier (exec/mesh_exec.py),
   with a GLOBAL live-executable budget (OTB_MAX_LIVE_PROGRAMS):
   eviction calls PjitFunction.clear_cache() so the XLA executable is
   actually released, deterministically, instead of the old
   "drop every cache every 25 tests" workaround in the TPC-DS suite.
   Keys are canonical fragment signatures: literal-masked plan
   structure + dtype tuple + size-class bucket (the pow2/quarter-step
   classes of storage/batch.py), so `WHERE k <= X` with a different
   constant — or the same fragment over a different-but-same-size-class
   batch — reuses the compiled executable.

2. Persistent compilation cache — enable_persistent_cache() points
   jax_compilation_cache_dir under the cluster datadir so process
   restarts, `ctl start`, and repeated bench runs skip the XLA compile
   entirely (bench.py's warm2 arm measures it).

3. AOT warmup — warm_async() runs lower-and-compile jobs on a
   background daemon thread, off the query path: PREPARE warms its
   mesh program (dist_session._warm_prepared), cluster start re-stages
   recovered tables (parallel/cluster.py), aot_compile() does
   jit(...).lower(args).compile() without executing.

4. Telemetry — per-tier hit/miss/compile/compile_ms/eviction counters
   surfaced by the otb_plancache stat view (parallel/statviews.py).

5. Retrace sanitizer — OTB_TRACECHECK=1 records every jit-tier put's
   quantized class components (join factors, size classes, batch
   classes) into a program census; save_census() merges it into
   analysis/program_census.json, where the retrace-witness lint pass
   cross-checks witnessed compiles against the static ladder
   predictions (analysis/cardinality.py).

The exact-statement plan cache (get_or_build, used by both sessions)
keeps its holder-attached storage but now feeds the same counters.
Mutation stays defensive: sessions on a CN server share these caches
across handler threads, so races must never fail a query.
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import queue
import threading
import time
from typing import Optional

from ..obs import trace as obs_trace
from ..sql.fingerprint import fingerprint, struct_key
from ..utils import locks

_LOCK = locks.RLock("exec.plancache._LOCK")
_SEQ = itertools.count()
_REGISTRY: list = []   # guarded_by: _LOCK  (jit caches under the budget)


def _live_budget() -> int:
    """Global cap on live compiled executables across all program
    tiers — set below the population where XLA:CPU's jit compiler was
    observed to segfault (a few hundred; round 5 hit it at ~66% of the
    TPC-DS suite)."""
    try:
        return int(os.environ.get("OTB_MAX_LIVE_PROGRAMS", "224"))
    except ValueError:
        return 224


def _fn_live(fn) -> int:
    """Live executables held by a jitted function (0 for tombstones)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return 1 if fn is not None else 0


def _entry_fns(value):
    """Jitted functions inside a cache value ((fn, meta) tuples or a
    bare fn); tolerant of None tombstones."""
    vals = value if isinstance(value, (tuple, list)) else (value,)
    return [v for v in vals if hasattr(v, "clear_cache")]


class ProgramCache:
    """Bounded LRU keyed by canonical fragment signature.  `jit=True`
    caches hold compiled programs and participate in the global
    live-executable budget; `jit=False` caches (plan/template tiers)
    only bound entry count and feed counters."""

    def __init__(self, name: str, max_entries: int, jit: bool = True):
        self.name = name
        self.max_entries = max_entries
        self.jit = jit
        self._d: dict = {}            # key -> [seq, value]
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        self.compile_ms = 0.0
        self.evictions = 0
        with _LOCK:
            if jit:
                _REGISTRY.append(self)

    # -- lookup / insert ------------------------------------------------
    def get(self, key):
        with _LOCK:
            ent = self._d.get(key)
            if ent is None:
                self.misses += 1
            else:
                ent[0] = next(_SEQ)
                self.hits += 1
        if obs_trace.ENABLED:
            obs_trace.event("program", tier=self.name,
                            hit=ent is not None)
        return None if ent is None else ent[1]

    def peek(self, key):
        """Lookup that refreshes LRU order but defers hit/miss
        accounting to count() — for callers whose hit criterion is
        richer than key presence (generation-checked entries)."""
        with _LOCK:
            ent = self._d.get(key)
            if ent is None:
                return None
            ent[0] = next(_SEQ)
            return ent[1]

    def count(self, hit: bool):
        with _LOCK:
            if hit:
                self.hits += 1
            else:
                self.misses += 1

    def put(self, key, value):
        with _LOCK:
            try:
                self._d[key] = [next(_SEQ), value]
            except TypeError:
                return value          # unhashable key: just don't cache
            if self.jit and tracecheck_enabled():
                _census_note(self, key)
            while len(self._d) > self.max_entries:
                self._evict_lru()
        if self.jit:
            trim_live()
        return value

    def replace(self, key, value):
        """Swap a value in place (permanent-fallback tombstones) without
        touching LRU order or eviction."""
        with _LOCK:
            ent = self._d.get(key)
            if ent is not None:
                for fn in _entry_fns(ent[1]):
                    try:
                        fn.clear_cache()
                    except Exception:
                        pass
                ent[1] = value
                if self.jit and tracecheck_enabled():
                    _census_forget(self, key)

    def pop(self, key):
        with _LOCK:
            ent = self._d.pop(key, None)
            if ent is not None and self.jit and tracecheck_enabled():
                _census_forget(self, key)
        if ent is not None:
            for fn in _entry_fns(ent[1]):
                try:
                    fn.clear_cache()
                except Exception:
                    pass

    # -- accounting -----------------------------------------------------
    def note_compile(self, n: int = 1, ms: float = 0.0):
        with _LOCK:
            self.compiles += n
            self.compile_ms += ms

    def record_call(self, fn, t0: float):
        """Post-execution compile detection: a grown per-fn cache means
        this call traced+compiled (a new shape/dtype bucket); attribute
        the call's wall time to compile_ms and re-check the budget."""
        after = _fn_live(fn)
        before = getattr(fn, "_otb_seen", 0)
        if after > before:
            dt = (time.perf_counter() - t0) * 1e3
            self.note_compile(after - before, dt)
            obs_trace.event("compile", tier=self.name, ms=round(dt, 3))
            try:
                fn._otb_seen = after
            except Exception:
                pass
            trim_live()

    def live(self) -> int:
        with _LOCK:
            return sum(_fn_live(fn) for _s, v in self._d.values()
                       for fn in _entry_fns(v))

    def __len__(self):
        return len(self._d)

    def clear(self):
        with _LOCK:
            keys = list(self._d)
        for k in keys:
            self.pop(k)

    # -- eviction -------------------------------------------------------
    def _evict_lru(self):
        # caller holds _LOCK
        if not self._d:
            return
        key = min(self._d, key=lambda k: self._d[k][0])
        _s, value = self._d.pop(key)
        self.evictions += 1
        if self.jit and tracecheck_enabled():
            _census_forget(self, key)
        for fn in _entry_fns(value):
            try:
                fn.clear_cache()
            except Exception:
                pass


def trim_live():
    """Enforce the global live-executable budget: evict globally-LRU
    entries (across every jit cache) that actually hold executables
    until the population fits.  Deterministic, targeted — replaces the
    conftest hack of dropping every cache every N tests."""
    budget = _live_budget()
    with _LOCK:
        for _ in range(4096):
            total = sum(c.live() for c in _REGISTRY)
            if total <= budget:
                return
            best = None
            for c in _REGISTRY:
                for k, (seq, v) in c._d.items():
                    if not any(_fn_live(fn) for fn in _entry_fns(v)):
                        continue
                    if best is None or seq < best[0]:
                        best = (seq, c, k)
            if best is None:
                return
            _seq, c, k = best
            _s, value = c._d.pop(k)
            c.evictions += 1
            if tracecheck_enabled():
                _census_forget(c, k)   # _REGISTRY holds jit caches only
            for fn in _entry_fns(value):
                try:
                    fn.clear_cache()
                except Exception:
                    pass


# ---------------------------------------------------------------------------
# retrace sanitizer (OTB_TRACECHECK=1): per-program compile census
# ---------------------------------------------------------------------------
_CENSUS: dict = {}        # guarded_by: _LOCK  (tier, frag, key) -> entry
_CENSUS_ATEXIT = [False]  # guarded_by: _LOCK


def tracecheck_enabled() -> bool:
    """OTB_TRACECHECK=1 arms the retrace sanitizer: every jit-tier
    ``put`` records its signature's quantized class components so the
    lint gate can cross-check witnessed compiles against the static
    ladder predictions (analysis/cardinality.py, retrace-witness) —
    the lock-witness pattern of utils/locks.py applied to program
    cardinality.  Read at use time, not import, so subprocess tests
    can flip it."""
    return os.environ.get("OTB_TRACECHECK", "").strip().lower() \
        in ("1", "on", "true", "yes")


def _census_classes(tier: str, key):
    """Split a program key into (classes, frag_key): the quantized
    size/factor components — each must be ladder-shaped — and the key
    with those positions masked out (the fragment signature whose
    class combinations share one compile budget).  Returns None for
    key shapes this extractor does not recognize."""
    if tier == "fused" and isinstance(key, tuple) and len(key) >= 6:
        # base_key(5) [+ ("__batch", class) | ("__morsel", class)]
        # + sorted factor items
        classes, tail = [], []
        for part in key[5:]:
            if (isinstance(part, tuple) and len(part) == 2
                    and part[0] == "__batch"):
                classes.append(("batch", part[1]))
                tail.append(("__batch", "*"))
            elif (isinstance(part, tuple) and len(part) == 2
                    and part[0] == "__morsel"):
                # the chunk-size class of a morsel stream — quantized
                # by storage/batch.py chunk_class, so the witness gate
                # can hold it to the ladder like any batch class
                classes.append(("chunk", part[1]))
                tail.append(("__morsel", "*"))
            elif isinstance(part, tuple):
                for it in part:
                    if isinstance(it, tuple) and len(it) == 2:
                        classes.append((f"factor:{it[0]}", it[1]))
                tail.append("*")
            else:
                tail.append(part)
        # table_sig (key[1]) carries store id()s and per-snapshot dict
        # sizes — execution environment, not fragment identity.  The
        # codec classes riding in it ARE witness material though: pull
        # them out first so the gate can hold encoding drift to the
        # quantized token enum (codec ladder promotions must mint
        # class-shaped keys, never raw-descriptor keys).
        for el in key[1]:
            if isinstance(el, tuple) and len(el) >= 4 \
                    and isinstance(el[3], tuple):
                for it in el[3]:
                    if isinstance(it, tuple) and len(it) == 2:
                        classes.append(
                            (f"codec:{el[0]}.{it[0]}", it[1]))
        frag = (key[0], "*", key[2], key[3], key[4]) + tuple(tail)
        return classes, frag
    if tier == "mesh" and isinstance(key, tuple) and len(key) == 9:
        # (runner_id, frags, exchanges, tables, factors, mults,
        #  gathers, baked, traced-types) — see mesh_exec.prog_key
        classes, tabs = [], []
        for el in key[3]:     # (table, padded, dicts, arrs, codecs)
            classes.append((f"pad:{el[0]}", el[1]))
            if len(el) >= 5 and isinstance(el[4], tuple):
                for it in el[4]:
                    if isinstance(it, tuple) and len(it) == 2:
                        classes.append(
                            (f"codec:{el[0]}.{it[0]}", it[1]))
            tabs.append((el[0], "*", el[2], el[3]))
        for label, part in (("factor", key[4]), ("mult", key[5]),
                            ("gather", key[6])):
            for k, v in part:
                classes.append((f"{label}:{k}", v))
        frag = ("*", key[1], key[2], tuple(tabs), "*", "*", "*",
                key[7], key[8])
        return classes, frag
    return None


def _census_note(cache: "ProgramCache", key) -> None:  # holds: _LOCK
    # the sanitizer must never fail a query
    try:
        split = _census_classes(cache.name, key)
        if split is None:
            classes, frag_fp = [], "?"
        else:
            classes, frag_fp = split[0], struct_key(split[1])
        kfp = struct_key(key)
        ent = _CENSUS.get((cache.name, frag_fp, kfp))
        if ent is None:
            _CENSUS[(cache.name, frag_fp, kfp)] = {
                "tier": cache.name, "frag": frag_fp, "key": kfp,
                "classes": [[d, v] for d, v in classes], "puts": 1}
        else:
            ent["puts"] += 1
        _census_arm_atexit()
    except Exception:
        pass


def _census_forget(cache: "ProgramCache", key) -> None:  # holds: _LOCK
    # an evicted program's later re-put is a legitimate recompile, not
    # a retrace — drop its census entry
    try:
        split = _census_classes(cache.name, key)
        frag_fp = "?" if split is None else struct_key(split[1])
        _CENSUS.pop((cache.name, frag_fp, struct_key(key)), None)
    except Exception:
        pass


def _census_arm_atexit() -> None:  # holds: _LOCK
    if _CENSUS_ATEXIT[0]:
        return
    _CENSUS_ATEXIT[0] = True
    if os.environ.get("OTB_TRACECHECK_REPORT", "").strip() or \
            os.environ.get("OTB_TRACECHECK_PERSIST", "").strip():
        atexit.register(save_census)


def census() -> list:
    """This process's witnessed program census entries (copies)."""
    with _LOCK:
        return [dict(e) for e in _CENSUS.values()]


def reset_census() -> None:
    with _LOCK:
        _CENSUS.clear()


def default_census_path() -> str:
    env = os.environ.get("OTB_TRACECHECK_REPORT", "").strip()
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(pkg, "analysis", "program_census.json")


def save_census(path: Optional[str] = None) -> dict:
    """Merge this process's program census into the report file (max
    puts per signature survives across shards/processes); the static
    pass cross-checks every witnessed class against the ladder
    predictions (analysis/cardinality.py, retrace-witness)."""
    path = path or default_census_path()
    merged = {(e["tier"], e["frag"], e["key"]): dict(e)
              for e in census()}
    try:
        with open(path, encoding="utf-8") as f:
            prior = json.load(f)
        for e in prior.get("entries", []):
            k = (e.get("tier"), e.get("frag"), e.get("key"))
            cur = merged.get(k)
            if cur is None:
                merged[k] = e
            else:
                cur["puts"] = max(cur.get("puts", 1),
                                  e.get("puts", 1))
    except (OSError, ValueError):
        pass
    data = {
        "comment": "program compile census (OTB_TRACECHECK=1 runs); "
                   "every witnessed class must be ladder-shaped and "
                   "every live signature must compile exactly once — "
                   "see analysis/cardinality.py (retrace-witness)",
        "entries": sorted(merged.values(),
                          key=lambda e: (str(e.get("tier")),
                                         str(e.get("frag")),
                                         str(e.get("key")))),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


# ---------------------------------------------------------------------------
# tier singletons
# ---------------------------------------------------------------------------
FUSED = ProgramCache("fused", max_entries=192)
MESH = ProgramCache("mesh", max_entries=128)
PLAN = ProgramCache("plan", max_entries=256, jit=False)
AUTOPREP = ProgramCache("autoprep", max_entries=256, jit=False)


def stats() -> list:
    """Per-tier counters for the otb_plancache view:
    (tier, hits, misses, compiles, compile_ms, evictions, live)."""
    out = []
    for c in (FUSED, MESH, PLAN, AUTOPREP):
        live = c.live() if c.jit else len(c)
        out.append((c.name, c.hits, c.misses, c.compiles,
                    round(c.compile_ms, 3), c.evictions, live))
    return out


def _metrics_samples():
    """Registry collector: the plancache counters as labeled samples
    (obs/metrics.py — the unified pane behind otb_metrics and the
    Prometheus exposition)."""
    for tier, hits, misses, compiles, compile_ms, ev, live in stats():
        lbl = {"tier": tier}
        yield ("otb_plancache_hits", lbl, hits)
        yield ("otb_plancache_misses", lbl, misses)
        yield ("otb_plancache_compiles", lbl, compiles)
        yield ("otb_plancache_compile_ms", lbl, compile_ms)
        yield ("otb_plancache_evictions", lbl, ev)
        yield ("otb_plancache_live", lbl, live)


from ..obs.metrics import REGISTRY as _METRICS  # noqa: E402
_METRICS.register_collector("plancache", _metrics_samples)


# ---------------------------------------------------------------------------
# persistent XLA compilation cache
# ---------------------------------------------------------------------------
_persist_dir: Optional[str] = None


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax's compilation cache at `path` (or $OTB_COMPILE_CACHE)
    so XLA compiles survive process restarts.  First caller wins — the
    cache dir is process-global; later calls with a different path are
    no-ops (the already-armed dir keeps serving)."""
    global _persist_dir
    env = os.environ.get("OTB_COMPILE_CACHE", "").strip()
    if env.lower() in ("0", "off", "none"):
        return None            # explicit operator opt-out
    if env:
        path = env             # env pins one dir across every caller
    if not path:
        return _persist_dir
    if _persist_dir is not None:
        return _persist_dir
    import jax
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip sub-second/small programs — exactly
        # the fragment programs this engine compiles by the hundreds
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except Exception:
        return _persist_dir
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches",
                          "all")
    except Exception:
        pass      # older jax: the executable cache alone still works
    _persist_dir = path
    return _persist_dir


def persistent_cache_dir() -> Optional[str]:
    return _persist_dir


# $OTB_COMPILE_CACHE arms the cache for ANY deployment shape (bench
# children, ad-hoc scripts, datadir-less sessions) without a call site
if os.environ.get("OTB_COMPILE_CACHE", "").strip():
    enable_persistent_cache()


# ---------------------------------------------------------------------------
# AOT warmup (background, off the query path)
# ---------------------------------------------------------------------------
_warm_q: "queue.Queue" = queue.Queue()
_warm_thread: Optional[threading.Thread] = None


def _warm_loop():
    while True:
        # warmup daemon idle dequeue, not a query-visible stall
        job = _warm_q.get()  # otblint: disable=wait-discipline
        try:
            job()
        except Exception:
            pass          # warmup must never surface errors
        finally:
            _warm_q.task_done()


def warm_async(job) -> None:
    """Run `job` (a no-arg callable that compiles something) on the
    warmup daemon thread."""
    global _warm_thread
    with _LOCK:
        if _warm_thread is None or not _warm_thread.is_alive():
            _warm_thread = threading.Thread(
                target=_warm_loop, daemon=True, name="plancache-warm")
            _warm_thread.start()
    _warm_q.put(job)


def warm_drain(timeout: float = 60.0) -> bool:
    """Block until queued warmup jobs finish (tests/bench)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _warm_q.unfinished_tasks == 0:
            return True
        time.sleep(0.01)
    return False


def aot_compile(fn, *args) -> bool:
    """jit(...).lower(args).compile() without executing: populates the
    persistent XLA cache so a later call of the same program skips the
    XLA compile (args may be jax.ShapeDtypeStructs — no data needed).
    Warm paths that hold REAL staged arrays prefer running the jitted
    fn once instead, which also fills its dispatch cache."""
    try:
        fn.lower(*args).compile()
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# exact-statement plan cache (the CachedPlanSource generic-plan arm)
# ---------------------------------------------------------------------------
_MAX = 256


def get_or_build(holder, attr: str, stmt, gen, build,
                 cacheable=lambda obj: True):
    """Return the cached object for (stmt, gen) on `holder.attr`, or
    build, insert, and return it.  Keyed by the EXACT statement
    (literals included, sql/fingerprint.py unmasked mode) plus a
    generation tuple covering DDL, stats, and the GUCs that shape
    planning.  `build()` runs at most once per call; uncacheable
    statements/objects just build (e.g. FQS/gidx plans, whose target
    node was chosen from DATA at plan time).  Feeds the PLAN tier's
    hit/miss counters (otb_plancache)."""
    cache = getattr(holder, attr, None)
    if cache is None:
        cache = {}
        setattr(holder, attr, cache)
    try:
        fp = fingerprint(stmt, mask_literals=False)
    except Exception:
        return build()
    hit = cache.get(fp)
    if hit is not None and hit[0] == gen:
        with _LOCK:
            PLAN.hits += 1
        if obs_trace.ENABLED:
            obs_trace.event("plancache", hit=True)
        return hit[1]
    with _LOCK:
        PLAN.misses += 1
    obs_trace.event("plancache", hit=False)
    obj = build()
    if obj is None or not cacheable(obj):
        return obj
    try:
        cache[fp] = (gen, obj)
        while len(cache) > _MAX:
            cache.pop(next(iter(cache)))
    except (KeyError, RuntimeError):
        pass      # concurrent evictors raced; the cache stays bounded
    return obj
