"""Generic ad-hoc plan cache shared by the single-node and cluster
sessions.

Reference analog: the generic-plan arm of CachedPlanSource
(utils/cache/plancache.c) applied to UNNAMED statements: repeated
identical SELECTs reuse the planned tree — and, through the fused/mesh
tiers' program memoization, the compiled XLA program.  Keyed by the
EXACT statement (literals included, sql/fingerprint.py unmasked mode)
plus a generation tuple covering DDL, stats, and the GUCs that shape
planning.  Mutation is defensive: sessions on a CN server share one
cluster-level cache across handler threads, so eviction races must
never fail a query.
"""

from __future__ import annotations

from ..sql.fingerprint import fingerprint

_MAX = 256


def get_or_build(holder, attr: str, stmt, gen, build,
                 cacheable=lambda obj: True):
    """Return the cached object for (stmt, gen) on `holder.attr`, or
    build, insert, and return it.  `build()` runs at most once per
    call; uncacheable statements/objects just build (e.g. FQS/gidx
    plans, whose target node was chosen from DATA at plan time)."""
    cache = getattr(holder, attr, None)
    if cache is None:
        cache = {}
        setattr(holder, attr, cache)
    try:
        fp = fingerprint(stmt, mask_literals=False)
    except Exception:
        return build()
    hit = cache.get(fp)
    if hit is not None and hit[0] == gen:
        return hit[1]
    obj = build()
    if obj is None or not cacheable(obj):
        return obj
    try:
        cache[fp] = (gen, obj)
        while len(cache) > _MAX:
            cache.pop(next(iter(cache)))
    except (KeyError, RuntimeError):
        pass      # concurrent evictors raced; the cache stays bounded
    return obj
