"""Row-level triggers + the minimal procedural layer they need.

Reference analog: commands/trigger.c (trigger firing around DML) +
src/pl/plpgsql (here: a statement-sequence SQL body, not a full
language — CREATE FUNCTION f() RETURNS TRIGGER AS 'stmt; stmt'
LANGUAGE SQL).  Bodies reference the affected row as NEW.col / OLD.col;
RAISE 'message' aborts the statement (the plpgsql RAISE EXCEPTION
surface).

Execution model: DML collects the affected row set (INSERT: the
incoming rows; UPDATE: old+new images; DELETE: old images), then for
each trigger on (table, event) and each row, the WHEN condition and the
body statements are rewritten with NEW./OLD. references replaced by the
row's literal values and executed through the session INSIDE the same
transaction — a trigger failure aborts the whole statement.  Set-based
engines fire per logical row like the reference does; the body
statements themselves run as normal (columnar) statements, so an
audit-insert or cascading update is still one engine statement per
affected row, not per touched byte.
"""

from __future__ import annotations

import threading

from ..sql import ast as A
from ..sql.parser import parse_sql
from .executor import ExecError
from ..utils import locks

_MAX_DEPTH = 8

_body_lock = locks.Lock("exec.triggers._body_lock")
_body_cache: dict[str, list] = {}   # guarded_by: _body_lock


def _parse_body(name: str, body: str) -> list:
    with _body_lock:
        hit = _body_cache.get(body)
    if hit is None:
        try:
            hit = parse_sql(body)
        except Exception as e:
            raise ExecError(f"function {name!r} body does not parse: "
                            f"{e}") from None
        with _body_lock:
            won = _body_cache.get(body)  # re-validate: parse race
            if won is not None:
                return won
            _body_cache[body] = hit
            if len(_body_cache) > 256:
                _body_cache.pop(next(iter(_body_cache)))
    return hit


def _lit(v) -> A.Node:
    if v is None:
        return A.Const(None, "null")
    if isinstance(v, bool):
        return A.Const(v, "bool")
    if isinstance(v, int):
        return A.Const(str(v), "int")
    if isinstance(v, float):
        return A.Const(repr(v), "num")
    return A.Const(str(v), "str")


def _subst_row(node, new_row: dict, old_row: dict):
    """Rewrite NEW.col / OLD.col references to row-value literals."""
    def fn(x):
        if isinstance(x, A.ColRef) and len(x.parts) == 2:
            q, c = x.parts
            if q == "new":
                if new_row is None or c not in new_row:
                    raise ExecError(f"NEW.{c} is not available here")
                return _lit(new_row[c])
            if q == "old":
                if old_row is None or c not in old_row:
                    raise ExecError(f"OLD.{c} is not available here")
                return _lit(old_row[c])
        return None
    return A.rewrite(node, fn)


def triggers_for(catalog, table: str, timing: str, event: str) -> list:
    return [tg for tg in catalog.triggers.values()
            if tg["table"] == table and tg["timing"] == timing
            and tg["event"] == event]


def has_triggers(catalog, table: str, event: str) -> bool:
    """Fast gate so trigger-free DML pays nothing (no OLD-row
    materialization, no firing pass)."""
    return any(tg["table"] == table and tg["event"] == event
               for tg in catalog.triggers.values())


def _eval_when(session, when: A.Node, new_row, old_row) -> bool:
    cond = _subst_row(when, new_row, old_row)
    sel = A.SelectStmt(items=[A.SelectItem(cond)], from_=[])
    rows = session._exec_stmt(sel).rows
    return bool(rows and rows[0][0])


def fire(session, catalog, table: str, timing: str, event: str,
         rows_new: "list | None", rows_old: "list | None",
         colnames: list):
    """Fire every (table, timing, event) trigger per affected row.
    rows_new/rows_old are aligned lists of row tuples (None when the
    event has no such image)."""
    tgs = triggers_for(catalog, table, timing, event)
    if not tgs:
        return
    depth = getattr(session, "_trigger_depth", 0)
    if depth >= _MAX_DEPTH:
        raise ExecError(
            f"trigger nesting exceeded {_MAX_DEPTH} levels "
            "(recursive trigger?)")
    n = len(rows_new) if rows_new is not None else len(rows_old)
    session._trigger_depth = depth + 1
    try:
        for tg in tgs:
            fn = catalog.functions.get(tg["func"])
            if fn is None:
                raise ExecError(
                    f"trigger {tg.get('name')!r} calls missing "
                    f"function {tg['func']!r}")
            body = _parse_body(tg["func"], fn["body"])
            when = None
            if tg.get("when"):
                when = parse_sql("select " + tg["when"])[0].items[0].expr
            for i in range(n):
                new_row = dict(zip(colnames, rows_new[i])) \
                    if rows_new is not None else None
                old_row = dict(zip(colnames, rows_old[i])) \
                    if rows_old is not None else None
                if when is not None and \
                        not _eval_when(session, when, new_row, old_row):
                    continue
                for stmt in body:
                    s2 = _subst_row(stmt, new_row, old_row)
                    if isinstance(s2, A.RaiseStmt):
                        raise ExecError(s2.message)
                    session._exec_stmt(s2)
    finally:
        session._trigger_depth = depth


def ddl(catalog, stmt):
    """Apply a trigger/function DDL statement to `catalog`; returns the
    command tag, or None when stmt is not a trigger DDL (reference:
    CreateFunction / CreateTrigger utility commands)."""
    if isinstance(stmt, A.CreateFunctionStmt):
        if stmt.returns != "trigger":
            raise ExecError("only RETURNS TRIGGER functions are "
                            "supported")
        if stmt.name in catalog.functions and not stmt.or_replace:
            raise ExecError(f"function {stmt.name!r} already exists")
        _parse_body(stmt.name, stmt.body)     # validate at DDL time
        catalog.functions[stmt.name] = {"body": stmt.body}
        return "CREATE FUNCTION"
    if isinstance(stmt, A.DropFunctionStmt):
        if stmt.name not in catalog.functions:
            if stmt.if_exists:
                return "DROP FUNCTION"
            raise ExecError(f"function {stmt.name!r} does not exist")
        users = [t for t, tg in catalog.triggers.items()
                 if tg["func"] == stmt.name]
        if users:
            raise ExecError(
                f"cannot drop function {stmt.name!r}: trigger "
                f"{users[0]!r} depends on it")
        del catalog.functions[stmt.name]
        return "DROP FUNCTION"
    if isinstance(stmt, A.CreateTriggerStmt):
        if stmt.table not in catalog.tables:
            raise ExecError(f"table {stmt.table!r} does not exist")
        if stmt.func not in catalog.functions:
            raise ExecError(f"function {stmt.func!r} does not exist")
        if stmt.name in catalog.triggers:
            raise ExecError(f"trigger {stmt.name!r} already exists")
        catalog.triggers[stmt.name] = {
            "name": stmt.name, "table": stmt.table,
            "timing": stmt.timing, "event": stmt.event,
            "when": stmt.when_src, "func": stmt.func}
        return "CREATE TRIGGER"
    if isinstance(stmt, A.DropTriggerStmt):
        tg = catalog.triggers.get(stmt.name)
        if tg is None or tg["table"] != stmt.table:
            if stmt.if_exists:
                return "DROP TRIGGER"
            raise ExecError(f"trigger {stmt.name!r} on "
                            f"{stmt.table!r} does not exist")
        del catalog.triggers[stmt.name]
        return "DROP TRIGGER"
    return None


_TRIGGER_DDL = (A.CreateFunctionStmt, A.DropFunctionStmt,
                A.CreateTriggerStmt, A.DropTriggerStmt)
