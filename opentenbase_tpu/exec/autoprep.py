"""Auto-prepare: raw-literal statements ride the prepared-plan path.

Reference analog: the reference answers UNPREPARED single-shard reads in
sub-ms because FQS ships the SQL text without a full plan cycle
(pgxc/plan/planner.c:390, execLight.c:34).  Here the equivalent is the
prepared-statement machinery (bound once with $n parameter columns, FQS
param router, traced-parameter XLA programs) — so the ad-hoc path
auto-parameterizes: WHERE-clause numeric/date literals are lifted into
Params, the resulting TEMPLATE keys a cluster-wide cache of Prepared
objects, and every statement that differs only in those literal values
reuses the same plan, router, and compiled program.

Only literal kinds whose parameter typing exactly matches the binder's
literal typing are lifted (int -> INT64, non-exponent numerics ->
DECIMAL(30, frac), exponent numerics -> FLOAT64, date literals ->
DATE).  Strings/bools/NULLs stay baked into the template (their binding
is context-dependent — dictionary predicates, 3VL), which keeps the
template fingerprint distinct per value, so correctness never depends
on the lift being complete.  Templates that fail to bind with abstract
params fall back to the normal plan path (and are remembered, so the
failed bind is paid once per template).
"""

from __future__ import annotations

import dataclasses

from ..catalog import types as T
from ..sql import ast as A


def _liftable_type(node):
    """SqlType a lifted literal should declare, or None to keep baked.
    Must mirror Binder._bind_const so param semantics == literal
    semantics."""
    if isinstance(node, A.Const):
        if node.kind == "int":
            return T.INT64
        if node.kind == "num":
            s = str(node.value)
            if "e" in s.lower():
                return T.FLOAT64
            frac = len(s.split(".")[1]) if "." in s else 0
            return T.decimal(30, frac)
        return None
    if isinstance(node, A.TypedConst) and node.type_name == "date":
        return T.DATE
    if isinstance(node, A.UnaryOp) and node.op == "-":
        inner = _liftable_type(node.arg)
        # negation is handled by _bind_arg; only numeric kinds
        if inner is not None and inner.kind != T.TypeKind.DATE:
            return inner
        return None
    return None


# node types whose subtrees keep literals baked: nested queries replan
# with their own cache entries; IN-lists need literal values at bind
# time (code-set membership); LIMIT/OFFSET are plan structure.
_OPAQUE = (A.SelectStmt, A.InExpr, A.ScalarSubquery, A.ExistsExpr,
           A.QuantifiedCmp, A.SubqueryRef)


def parameterize(stmt: A.SelectStmt):
    """Lift WHERE literals of the top-level query into Params.
    Returns (template_stmt, arg_nodes, param_types) or None when
    nothing lifted."""
    if stmt.where is None:
        return None
    args: list = []
    types: dict = {}

    def lift(node):
        if isinstance(node, _OPAQUE):
            return node
        t = _liftable_type(node)
        if t is not None:
            args.append(node)
            idx = len(args)
            types[idx] = t
            return A.Param(idx)
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            changed = {}
            for f in dataclasses.fields(node):
                v = getattr(node, f.name)
                nv = lift(v)
                if nv is not v:
                    changed[f.name] = nv
            if changed:
                return dataclasses.replace(node, **changed)
            return node
        if isinstance(node, list):
            out = [lift(x) for x in node]
            return out if any(a is not b for a, b in zip(out, node)) \
                else node
        if isinstance(node, tuple):
            out = tuple(lift(x) for x in node)
            return out if any(a is not b for a, b in zip(out, node)) \
                else node
        return node

    new_where = lift(stmt.where)
    if not args:
        return None
    template = dataclasses.replace(stmt, where=new_where)
    return template, args, types


def cached_template(cluster, key, gen, build):
    """Cluster-wide Prepared-template cache, backed by the shared
    program-cache subsystem (exec/plancache.py AUTOPREP tier) so
    template reuse shows up in otb_plancache next to the compiled-
    program tiers it feeds.  `gen` is the plan-cache generation (DDL +
    stats + GUCs): a stale entry counts as a miss and rebuilds.  A
    None result is cached too — a template that can't bind with
    abstract params is remembered, so the failed bind is paid once."""
    from .plancache import AUTOPREP
    full = (id(cluster), key)
    ent = AUTOPREP.peek(full)
    if ent is not None and ent[0] == gen:
        AUTOPREP.count(hit=True)
        return ent[1]
    AUTOPREP.count(hit=False)
    prep = build()
    # gen/prep ride in the VALUE by design: the generation is validated
    # at peek (ent[0] == gen above), so it need not be in the key.
    AUTOPREP.put(full, (gen, prep))  # otblint: disable=program-key
    return prep
