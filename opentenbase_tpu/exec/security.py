"""Column masking + fine-grained audit (the security block).

Reference analog: utils/misc/datamask.c (transparent column masking —
values are replaced as they leave the engine, while joins, predicates
and storage operate on real data) and audit/audit_fga.c (fine-grained
audit: an audit record fires when a statement touches rows matching a
policy predicate).

Masking is a PROJECTION REWRITE in the binder (sql/analyze.py): every
query target that resolves to a masked (table, column) is replaced by
the mask expression bound in the same scope, so SELECTs, joins, views
and INSERT..SELECT all observe masked output while WHERE/GROUP BY/join
keys stay exact.  Internal DML reads (UPDATE's new-row scan, trigger
OLD images, constraint checks) bind with apply_masks=False — masked
output must never be written back.  `set bypass_datamask = on`
(cluster GUC — the plan caches key on GUCs, so flipping it replans)
disables masking for maintenance.

FGA fires post-statement: a SELECT whose FROM references a policy's
table runs `count(*)` of the policy predicate (conjoined with the
statement's WHERE for single-table reads) and writes an audit record
when matches exist.
"""

from __future__ import annotations

from ..sql import ast as A
from .executor import ExecError


def ddl(catalog, stmt):
    """Apply mask / audit-policy DDL; returns command tag or None."""
    if isinstance(stmt, A.CreateMaskStmt):
        if stmt.table not in catalog.tables:
            raise ExecError(f"table {stmt.table!r} does not exist")
        td = catalog.table(stmt.table)
        if not td.has_column(stmt.column):
            raise ExecError(f"column {stmt.column!r} not in "
                            f"{stmt.table!r}")
        if stmt.name in catalog.masks:
            raise ExecError(f"mask {stmt.name!r} already exists")
        if any(m["table"] == stmt.table and m["column"] == stmt.column
               for m in catalog.masks.values()):
            raise ExecError(f"column {stmt.table}.{stmt.column} is "
                            "already masked")
        from ..sql.parser import Parser
        try:
            Parser(stmt.expr_src).expr()
        except Exception as e:
            raise ExecError(
                f"mask expression does not parse: {e}") from None
        catalog.masks[stmt.name] = {"table": stmt.table,
                                    "column": stmt.column,
                                    "expr": stmt.expr_src}
        return "CREATE MASK"
    if isinstance(stmt, A.DropMaskStmt):
        if stmt.name not in catalog.masks:
            if stmt.if_exists:
                return "DROP MASK"
            raise ExecError(f"mask {stmt.name!r} does not exist")
        del catalog.masks[stmt.name]
        return "DROP MASK"
    if isinstance(stmt, A.CreateAuditPolicyStmt):
        if stmt.table not in catalog.tables:
            raise ExecError(f"table {stmt.table!r} does not exist")
        if stmt.name in catalog.fga_policies:
            raise ExecError(f"audit policy {stmt.name!r} already "
                            "exists")
        from ..sql.parser import Parser
        try:
            Parser(stmt.pred_src).expr()
        except Exception as e:
            raise ExecError(
                f"policy predicate does not parse: {e}") from None
        catalog.fga_policies[stmt.name] = {"table": stmt.table,
                                           "pred": stmt.pred_src}
        return "CREATE AUDIT POLICY"
    if isinstance(stmt, A.DropAuditPolicyStmt):
        if stmt.name not in catalog.fga_policies:
            if stmt.if_exists:
                return "DROP AUDIT POLICY"
            raise ExecError(
                f"audit policy {stmt.name!r} does not exist")
        del catalog.fga_policies[stmt.name]
        return "DROP AUDIT POLICY"
    return None


_SECURITY_DDL = (A.CreateMaskStmt, A.DropMaskStmt,
                 A.CreateAuditPolicyStmt, A.DropAuditPolicyStmt)


def _stmt_tables(stmt: A.SelectStmt) -> list:
    out = []
    for f in stmt.from_ or []:
        stack = [f]
        while stack:
            x = stack.pop()
            if isinstance(x, A.TableRef):
                out.append(x.name)
            for attr in ("left", "right"):
                c = getattr(x, attr, None)
                if c is not None:
                    stack.append(c)
    return out


def fga_check(session, stmt: A.SelectStmt):
    """Post-statement FGA pass: for every policy on a referenced table,
    count predicate matches (conjoined with the WHERE for single-table
    reads) and emit an audit record on a hit.  Depth-guarded: the
    count query itself must not re-trigger FGA."""
    catalog = session.cluster.catalog
    if not catalog.fga_policies or getattr(session, "_in_fga", False):
        return
    audit = getattr(session.cluster, "audit", None)
    if audit is None:
        return
    tables = _stmt_tables(stmt)
    if not tables:
        return
    from ..sql.parser import Parser
    session._in_fga = True
    try:
        for name, pol in list(catalog.fga_policies.items()):
            if pol["table"] not in tables:
                continue
            pred = Parser(pol["pred"]).expr()
            where = pred
            if (len(tables) == 1 and stmt.where is not None
                    and len(stmt.from_ or []) == 1):
                where = A.BoolExpr("and", [pred, stmt.where])

            def count(w):
                sel = A.SelectStmt(
                    items=[A.SelectItem(
                        A.FuncCall("count", [], star=True))],
                    from_=[A.TableRef(pol["table"])], where=w)
                return session._exec_stmt(sel).rows[0][0]
            try:
                n = count(where)
            except Exception:
                # the statement's WHERE may not bind in the count
                # query's scope (aliases): fall back to the policy
                # predicate alone — over-reporting beats silently
                # missing the exact event FGA exists to capture
                if where is pred:
                    continue    # policy predicate itself broken: skip
                try:
                    n = count(pred)
                except Exception:
                    continue
            if n:
                audit.record("FGA", f"policy={name} "
                                    f"table={pol['table']} rows={n}")
    finally:
        session._in_fga = False
