"""Constraint enforcement: NOT NULL, CHECK, FOREIGN KEY.

Reference analog: ExecConstraints (executor/execMain.c) for NOT
NULL/CHECK and RI_FKey_check triggers (utils/adt/ri_triggers.c) for
foreign keys.

TPU-first shape: instead of per-tuple trigger firings, validation is
SET-BASED — one engine query per constraint per statement, running
inside the writing transaction (it sees the txn's own rows through
normal MVCC).  A CHECK is `count(rows where NOT expr)`; a FOREIGN KEY
is one anti-join (`child LEFT JOIN parent ... WHERE parent IS NULL`).
Both compile onto the same device data plane as user queries, so
constraint checking is columnar and batched, not per-row host work.
NULL CHECK results pass (SQL: only definite FALSE violates); NULL FK
values pass (MATCH SIMPLE).
"""

from __future__ import annotations

import threading

from ..sql import ast as A
from ..sql.parser import Parser
from .executor import ExecError
from ..utils import locks


class ConstraintViolation(ExecError):
    pass


_check_lock = locks.Lock("exec.constraints._check_lock")
_check_cache: dict[tuple, A.Node] = {}   # guarded_by: _check_lock


def _parse_check(table: str, src: str) -> A.Node:
    key = (table, src)
    with _check_lock:
        expr = _check_cache.get(key)
    if expr is None:
        expr = Parser(src).expr()
        with _check_lock:
            won = _check_cache.get(key)  # re-validate: parse race
            if won is not None:
                return won
            _check_cache[key] = expr
            if len(_check_cache) > 512:
                _check_cache.pop(next(iter(_check_cache)))
    return expr


def check_not_null(td, coldata: dict, n: int):
    """Host-side scan of the incoming column data (the one per-value
    pass that cannot be a query — the rows aren't stored yet)."""
    import numpy as np
    for c in td.columns:
        if c.nullable or c.name not in coldata:
            continue
        vals = coldata[c.name]
        if isinstance(vals, np.ndarray):
            bad = vals.dtype == object and any(v is None for v in vals)
        else:
            bad = any(v is None for v in vals)
        if bad:
            raise ConstraintViolation(
                f"null value in column {c.name!r} of relation "
                f"{td.name!r} violates not-null constraint")


def validate_after_write(run_query, catalog, table: str,
                         kind: str = "insert"):
    """Run every CHECK and FK that a write of `kind` to `table` could
    violate, via `run_query(select_stmt) -> rows` executing INSIDE the
    writing transaction.  An INSERT can break the table's own CHECKs
    and its child-role FKs; a DELETE can only orphan OTHER tables'
    rows (parent-role).  UPDATE runs both legs through its
    delete+insert decomposition."""
    td = catalog.table(table)
    if kind == "insert":
        for src in td.checks:
            expr = _parse_check(td.name, src)
            sel = A.SelectStmt(
                items=[A.SelectItem(
                    A.FuncCall("count", [], star=True))],
                from_=[A.TableRef(td.name)],
                where=A.UnaryOp("not", expr))
            bad = run_query(sel)[0][0]
            if bad:
                raise ConstraintViolation(
                    f"new row for relation {td.name!r} violates check "
                    f"constraint ({src}) [{bad} row(s)]")
        # FKs where `table` is the child
        _fk_orphan_checks(run_query, catalog, td, td.fks)
        return
    # delete: FKs where `table` is the referenced parent.  Self-
    # referencing FKs are included (other == table): the anti-join sees
    # the txn's own deletes through MVCC, so deleting a parent together
    # with its children in one statement still passes, while deleting
    # only the parent of a surviving same-table child is rejected
    # (reference: ri_triggers.c enforces self-FKs identically).
    # A DELETE against a partition CHILD can orphan rows referencing
    # its partitioned parent — FK targets resolve through the parent
    # name, so include it in the referenced set.
    from ..parallel.partition import parent_of
    targets = {table}
    hit = parent_of(catalog, table)
    if hit is not None:
        targets.add(hit[0])
    for other in catalog.tables.values():
        refs = [fk for fk in other.fks if fk["ref_table"] in targets]
        if not refs:
            continue
        # partition children inherit the parent's FKs, but the
        # parent-level anti-join already covers all child rows (a
        # parent reference binds as the union of its partitions) —
        # skip the child copies to avoid one redundant scan per
        # partition per DELETE
        ohit = parent_of(catalog, other.name)
        if ohit is not None:
            parent_fks = catalog.tables[ohit[0]].fks
            refs = [fk for fk in refs if fk not in parent_fks]
        if refs:
            _fk_orphan_checks(run_query, catalog, other, refs)


def _fk_orphan_checks(run_query, catalog, child_td, fks):
    for fk in fks:
        if fk["ref_table"] not in catalog.tables:
            raise ConstraintViolation(
                f"referenced table {fk['ref_table']!r} does not exist")
        eqs = [A.BinOp("=", A.ColRef(("__c", fc)),
                       A.ColRef(("__p", rc)))
               for fc, rc in zip(fk["cols"], fk["ref_cols"])]
        on = eqs[0] if len(eqs) == 1 else A.BoolExpr("and", eqs)
        conds = [A.NullTest(A.ColRef(("__c", fc)), False)
                 for fc in fk["cols"]]
        # orphans: child rows with non-NULL keys and no parent match
        conds.append(A.NullTest(A.ColRef(("__p", fk["ref_cols"][0])),
                                True))
        where = conds[0] if len(conds) == 1 \
            else A.BoolExpr("and", conds)
        sel = A.SelectStmt(
            items=[A.SelectItem(A.FuncCall("count", [], star=True))],
            from_=[A.JoinRef(
                "left",
                A.TableRef(child_td.name, alias="__c"),
                A.TableRef(fk["ref_table"], alias="__p"),
                on)],
            where=where)
        orphans = run_query(sel)[0][0]
        if orphans:
            raise ConstraintViolation(
                f"insert or update on table {child_td.name!r} "
                f"violates foreign key constraint: {orphans} row(s) "
                f"reference missing {fk['ref_table']}"
                f"({', '.join(fk['ref_cols'])})")


def tables_needing_validation(catalog, table: str,
                              kind: str = "insert") -> bool:
    """Fast gate: does a write of `kind` to `table` require any
    query-based validation at all?  (The common constraint-free path
    must not pay a catalog scan per insert.)"""
    td = catalog.table(table)
    if kind == "insert":
        return bool(td.checks or td.fks)
    from ..parallel.partition import parent_of
    targets = {table}
    hit = parent_of(catalog, table)
    if hit is not None:
        targets.add(hit[0])
    return any(fk["ref_table"] in targets
               for other in catalog.tables.values()
               for fk in other.fks)


def referencing_tables(catalog, table: str) -> list:
    """Tables holding a FOREIGN KEY that references `table`."""
    return [other.name for other in catalog.tables.values()
            if other.name != table and any(
                fk["ref_table"] == table for fk in other.fks)]


def drop_guards(catalog, table: str, action: str = "drop"):
    """DROP/TRUNCATE of an FK-referenced parent would poison every
    later write to the children (reference: dependency.c
    DEPENDENCY_NORMAL restrict; heap_truncate_check_FKs)."""
    refs = referencing_tables(catalog, table)
    if refs:
        raise ConstraintViolation(
            f"cannot {action} table {table!r}: referenced by a "
            f"foreign key on {refs[0]!r}")


def column_drop_guards(catalog, table: str, column: str):
    """A column used by a CHECK or FOREIGN KEY cannot be dropped or
    renamed (no DROP CONSTRAINT surface to recover with)."""
    td = catalog.table(table)
    for src in td.checks:
        expr = _parse_check(td.name, src)
        cols = {c.split(".", 1)[-1] for c in _expr_col_names(expr)}
        if column in cols:
            raise ConstraintViolation(
                f"cannot drop column {column!r}: used by check "
                f"constraint ({src})")
    for fk in td.fks:
        if column in fk["cols"]:
            raise ConstraintViolation(
                f"cannot drop column {column!r}: part of a foreign "
                "key")
    for other in catalog.tables.values():
        for fk in other.fks:
            if fk["ref_table"] == table and column in fk["ref_cols"]:
                raise ConstraintViolation(
                    f"cannot drop column {column!r}: referenced by a "
                    f"foreign key on {other.name!r}")


def _expr_col_names(node) -> set:
    out = set()
    stack = [node]
    while stack:
        x = stack.pop()
        if isinstance(x, A.ColRef):
            out.add(x.parts[-1])
            continue
        if hasattr(x, "__dataclass_fields__"):
            for f in x.__dataclass_fields__:
                stack.append(getattr(x, f))
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
    return out
