"""Device-resident columnar buffer pool: version-keyed HBM residency.

Reference analog: the buffer manager (src/backend/storage/buffer) — the
reference keeps hot heap pages pinned in shared_buffers so executors
never re-read disk for unchanged data.  Here the device HBM plays that
role for host-RAM chunk storage: staged (padded, concatenated, possibly
mesh-sharded) device columns stay resident ACROSS queries, keyed by the
per-store monotonic `version` counter (storage/store.py — bumped on
every mutation, process-globally unique so a recycled id() can never
alias).  The round-5 bench showed why: the mesh tier re-uploaded a full
host snapshot of every referenced table per query and ran Q1 at 0.27-
0.51 GB/s effective bandwidth — staging, not compute, was the bottleneck.

One pool serves every execution tier:

- single-device entries (exec/executor.py DeviceTableCache facade):
  per-store padded device columns, the fused tier and FQS scans read
  them; staged once per (store, version, column set).
- mesh entries (exec/mesh_exec.py): per-runner sharded arrays + union
  dictionaries + per-DN counts, keyed by the per-DN version tuple.
- host snapshots: the full live-row concatenation one store ships to
  the mesh owner (net/dn_server.py stage_table) or slices for spill
  passes (exec/spill.py) — version-keyed so an unchanged table never
  re-concatenates.

Budget + eviction mirror the compiled-program subsystem
(exec/plancache.py): one byte budget (OTB_DEVICE_CACHE_BYTES) over all
device entries, LRU eviction across both tiers; host snapshots have
their own smaller budget (OTB_HOST_SNAPSHOT_BYTES).

Invalidation is exact and lazy: DML/DDL/vacuum bump the store version,
the stale entry is detected (and dropped or tail-patched) on next
access; DROP/TRUNCATE paths call invalidate() eagerly so big tables
release HBM immediately.  Append-only growth takes the incremental
path: TableStore's mutation log proves every change since the cached
version touched only rows past the cached count, so staging uploads
just the tail instead of re-shipping the prefix (the dominant OLTP/
bulk-load pattern: INSERT then re-query).

Telemetry per table — hits / misses / bytes_live / evictions /
invalidations — surfaces as the otb_buffercache stat view
(parallel/statviews.py), next to otb_plancache.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import weakref

import numpy as np

from ..obs import trace as obs_trace
from ..obs import xray as obs_xray
from ..utils import locks, snapcheck
from . import codec

_LOCK = locks.RLock("storage.bufferpool._LOCK")
_SEQ = itertools.count()

_SYS_COLS = ("__xmin_ts", "__xmax_ts", "__xmin_txid", "__xmax_txid")
_NULL = "__null."


def _budget() -> int:
    """Byte budget over all device-resident entries (both tiers)."""
    try:
        return int(os.environ.get("OTB_DEVICE_CACHE_BYTES",
                                  str(8 << 30)))
    except ValueError:
        return 8 << 30


def _host_budget() -> int:
    """Byte budget for cached host snapshots (host RAM, not HBM)."""
    try:
        return int(os.environ.get("OTB_HOST_SNAPSHOT_BYTES",
                                  str(1 << 30)))
    except ValueError:
        return 1 << 30


@dataclasses.dataclass
class DevEntry:
    """Single-device tier: one store's padded device columns."""
    table: str
    version: int
    arrs: dict            # staged name -> device array [padded, ...]
    n: int                # live (staged) row count
    null_at_cache: set    # store.null_columns when staged
    nbytes: int           # actual device bytes (post-encoding)
    pins: int = 0         # refcount: >0 bars eviction (resident build
    # side of a streaming join, exec/morsel.py); guarded_by: _LOCK
    pins_by: dict = dataclasses.field(default_factory=dict)
    # consumer token -> refcount; sums to `pins`; guarded_by: _LOCK
    encs: dict = dataclasses.field(default_factory=dict)
    # staged name -> storage/codec.Enc for encoded columns (tail path)
    bytes_logical: int = 0  # unencoded bytes these arrays represent


@dataclasses.dataclass
class ChunkEntry:
    """Morsel tier: one fixed-shape row-range window of a store's host
    columns, staged to device.  All chunks of a stream share one padded
    shape (`chunk_rows`, storage/batch.py chunk_class) so the compiled
    per-chunk program never retraces; `live` is the real row count of
    this window (the tail chunk zero-pads).  Pinned while a stream
    holds it — eviction skips pinned entries."""
    table: str
    version: int
    start: int            # first source row of the window
    chunk_rows: int       # padded window shape (chunk_class-quantized)
    live: int             # real rows in [start, start+live)
    arrs: dict            # staged name -> device array [chunk_rows,...]
    nbytes: int           # actual device bytes (post-encoding)
    pins: int = 0         # guarded_by: _LOCK
    pins_by: dict = dataclasses.field(default_factory=dict)
    # consumer token -> refcount: a shared morsel stream
    # (exec/share.py) pins one window once per consumer, and a
    # consumer erroring mid-stream can only release its OWN pins —
    # never a pin another fragment is still probing; guarded_by: _LOCK
    bytes_logical: int = 0  # unencoded bytes this window represents


@dataclasses.dataclass
class MeshEntry:
    """Mesh tier: one table's sharded arrays + union-dict state."""
    table: str
    vkey: tuple           # per-DN store versions at staging time
    staged: object        # exec/mesh_exec._StagedTable
    counts: list          # per-DN live row counts
    dict_state: dict      # TEXT col -> {"index", "luts", "dn_lens"}
    null_columns: set     # union null-column set at staging time
    nbytes: int           # actual device bytes (post-encoding)
    encs: dict = dataclasses.field(default_factory=dict)
    # staged name -> storage/codec.Enc (incremental tail path)
    bytes_logical: int = 0  # unencoded bytes these shards represent


class DeviceBufferPool:
    """Version-keyed device residency with one LRU byte budget."""

    def __init__(self):
        self._dev: dict = {}    # id(store) -> [seq, DevEntry]
        self._mesh: dict = {}   # (runner_id, table) -> [seq, MeshEntry]
        self._host: dict = {}   # id(store) -> [seq, snapshot, nbytes]
        # morsel chunk windows: (id(store), start, chunk_rows,
        # names_key) -> [seq, ChunkEntry]
        self._chunks: dict = {}
        # entries must not outlive their owners: a weakref per store /
        # mesh runner drops the owner's entries at GC, so the pool never
        # pins device arrays for dead nodes (the per-node caches this
        # replaces died with their nodes; the shared pool must match)
        self._refs: dict = {}   # id(owner) -> weakref
        # table -> [hits, misses, evictions, invalidations, pins,
        # unpins]
        self._stats: dict[str, list] = {}
        self.uploaded_bytes = 0   # cumulative host->device bytes staged
        self.tail_rows = 0        # rows staged via the incremental path
        # pin ledger (the PR-10 slot-ledger pattern): every pin must be
        # balanced by an unpin, and eviction must never destroy a
        # pinned entry silently.  pins_total == unpins_total +
        # live-pinned (in-dict entries + orphans invalidation popped
        # while still pinned — their holders unpin through the entry
        # object they kept).
        self._pins_total = 0      # guarded_by: _LOCK
        self._unpins_total = 0    # guarded_by: _LOCK
        self._orphans: list = []  # guarded_by: _LOCK — popped-but-pinned

    def _watch_store(self, store):
        # caller holds _LOCK
        key = id(store)
        if key in self._refs:
            return

        def drop(_r, pool=weakref.ref(self), key=key):
            p = pool()
            if p is None:
                return
            with _LOCK:
                p._dev.pop(key, None)
                p._host.pop(key, None)
                for ck in [k for k in p._chunks if k[0] == key]:
                    p._chunks.pop(ck, None)
                p._refs.pop(key, None)
        try:
            self._refs[key] = weakref.ref(store, drop)
        except TypeError:
            pass

    def _watch_runner(self, runner):
        # caller holds _LOCK
        key = id(runner)
        if key in self._refs:
            return

        def drop(_r, pool=weakref.ref(self), key=key):
            p = pool()
            if p is None:
                return
            with _LOCK:
                for k in [k for k in p._mesh if k[0] == key]:
                    p._mesh.pop(k, None)
                p._refs.pop(key, None)
        try:
            self._refs[key] = weakref.ref(runner, drop)
        except TypeError:
            pass

    # -- accounting -----------------------------------------------------
    def _tstats(self, table: str) -> list:
        s = self._stats.get(table)
        if s is None:
            s = self._stats[table] = [0, 0, 0, 0, 0, 0]
        elif len(s) < 6:
            s.extend([0] * (6 - len(s)))
        return s

    def note_upload(self, nbytes: int, tail_rows: int = 0):
        with _LOCK:
            self.uploaded_bytes += int(nbytes)
            self.tail_rows += int(tail_rows)
        if nbytes:
            obs_trace.event("upload", bytes=int(nbytes),
                            tail_rows=int(tail_rows))

    def stats_rows(self) -> list[tuple]:
        """(table, hits, misses, bytes_live, evictions, invalidations,
        pinned, pins, unpins, bytes_logical, bytes_resident) rows for
        the otb_buffercache view (system otb_ tables omitted).
        `pinned` is the live pinned-entry count; pins/unpins are the
        cumulative refcount ledger; bytes_logical is what the resident
        entries would occupy UNENCODED vs bytes_resident, the actual
        post-encoding device bytes (== bytes_live) — their ratio is the
        effective-cache multiplier the codecs buy.  Columns append so
        positional consumers of the original six stay valid."""
        with _LOCK:
            live: dict[str, int] = {}
            logical: dict[str, int] = {}
            pinned: dict[str, int] = {}

            def acct(e):
                live[e.table] = live.get(e.table, 0) + e.nbytes
                logical[e.table] = logical.get(e.table, 0) \
                    + (e.bytes_logical or e.nbytes)

            for _s, e in self._dev.values():
                acct(e)
                if e.pins > 0:
                    pinned[e.table] = pinned.get(e.table, 0) + 1
            for _s, e in self._mesh.values():
                acct(e)
            for _s, e in self._chunks.values():
                acct(e)
                if e.pins > 0:
                    pinned[e.table] = pinned.get(e.table, 0) + 1
            rows = []
            for t in sorted(set(self._stats) | set(live)):
                if t.startswith("otb_"):
                    continue
                h, m, ev, inv, pi, up = self._tstats(t) \
                    if t in self._stats else (0, 0, 0, 0, 0, 0)
                rows.append((t, h, m, live.get(t, 0), ev, inv,
                             pinned.get(t, 0), pi, up,
                             logical.get(t, 0), live.get(t, 0)))
            return rows

    def totals(self) -> dict:
        with _LOCK:
            return {
                "hits": sum(s[0] for s in self._stats.values()),
                "misses": sum(s[1] for s in self._stats.values()),
                "evictions": sum(s[2] for s in self._stats.values()),
                "invalidations": sum(s[3] for s in self._stats.values()),
                "bytes_live": sum(e.nbytes for _s, e in
                                  self._dev.values())
                + sum(e.nbytes for _s, e in self._mesh.values())
                + sum(e.nbytes for _s, e in self._chunks.values()),
                "bytes_logical": sum(
                    (e.bytes_logical or e.nbytes)
                    for tier in (self._dev, self._mesh, self._chunks)
                    for _s, e in tier.values()),
                "uploaded_bytes": self.uploaded_bytes,
                "tail_rows": self.tail_rows,
                "pins": self._pins_total,
                "unpins": self._unpins_total,
                "pinned_live": self._live_pinned_locked(),
                "chunks_live": len(self._chunks),
            }

    def clear(self):
        """Drop everything (tests)."""
        with _LOCK:
            self._dev.clear()
            self._mesh.clear()
            self._host.clear()
            self._chunks.clear()
            self._refs.clear()
            self._orphans.clear()
            self._pins_total = 0
            self._unpins_total = 0

    # -- pin ledger -----------------------------------------------------
    def _live_pinned_locked(self) -> int:
        # caller holds _LOCK
        return (sum(e.pins for _s, e in self._dev.values())
                + sum(e.pins for _s, e in self._chunks.values())
                + sum(e.pins for e in self._orphans))

    def _note_pin_locked(self, entry, table: str, consumer=None):
        # caller holds _LOCK
        entry.pins += 1
        entry.pins_by[consumer] = entry.pins_by.get(consumer, 0) + 1
        self._pins_total += 1
        self._tstats(table)[4] += 1

    def _note_unpin_locked(self, entry, table: str, consumer=None):
        # caller holds _LOCK
        held = entry.pins_by.get(consumer, 0)
        assert held > 0, (
            f"bufferpool: unpin for {table} by consumer {consumer!r} "
            f"holding no pin (holders: {sorted(map(repr, entry.pins_by))})")
        entry.pins -= 1
        assert entry.pins >= 0, \
            f"bufferpool: unbalanced unpin for {table}"
        if held == 1:
            del entry.pins_by[consumer]
        else:
            entry.pins_by[consumer] = held - 1
        self._unpins_total += 1
        self._tstats(table)[5] += 1
        if entry.pins == 0:
            # identity filter: dataclass __eq__ would compare arrays
            self._orphans = [o for o in self._orphans if o is not entry]

    def check_pin_ledger(self):
        """Ledger invariant (mirrors the PR-10 slot ledgers): every pin
        is either balanced by an unpin or visible as a live pinned
        entry — eviction/invalidation can never make a pin disappear —
        and every live entry's total refcount equals the sum of its
        per-consumer counts, all positive (a consumer can never hold a
        negative balance or release another consumer's pin)."""
        with _LOCK:
            live = self._live_pinned_locked()
            assert self._pins_total == self._unpins_total + live, (
                f"bufferpool pin ledger broken: pins={self._pins_total} "
                f"unpins={self._unpins_total} live={live}")
            entries = ([e for _s, e in self._dev.values()]
                       + [e for _s, e in self._chunks.values()]
                       + list(self._orphans))
            for e in entries:
                assert e.pins == sum(e.pins_by.values()), (
                    f"bufferpool pin ledger broken for {e.table}: "
                    f"pins={e.pins} != per-consumer "
                    f"{dict(e.pins_by)}")
                assert all(c > 0 for c in e.pins_by.values()), (
                    f"bufferpool pin ledger broken for {e.table}: "
                    f"non-positive consumer count {dict(e.pins_by)}")
            return {"pins": self._pins_total,
                    "unpins": self._unpins_total, "live": live}

    # -- eviction -------------------------------------------------------
    def _evictable_locked(self) -> list:
        """(kind, key, seq, entry) over every UNPINNED device entry —
        pinned entries (streaming joins' resident build sides, in-flight
        morsel chunks) are wired down and never eviction candidates."""
        return ([("dev", k, s, e)
                 for k, (s, e) in self._dev.items() if e.pins == 0]
                + [("mesh", k, s, e)
                   for k, (s, e) in self._mesh.items()]
                + [("chunk", k, s, e)
                   for k, (s, e) in self._chunks.items()
                   if e.pins == 0])

    def _pop_entry_locked(self, kind: str, key):
        d = {"dev": self._dev, "mesh": self._mesh,
             "chunk": self._chunks}[kind]
        d.pop(key, None)

    def trim(self):
        """Enforce the device byte budget: evict globally-LRU UNPINNED
        entries (single-device, mesh and chunk tiers) until the
        resident population fits.  A lone over-budget entry stays — the
        active query holds references anyway, so evicting it frees
        nothing."""
        budget = _budget()
        with obs_xray.wait_event("bufpool-evict"), _LOCK:
            while True:
                items = self._evictable_locked()
                resident = (
                    sum(e.nbytes for _s, e in self._dev.values())
                    + sum(e.nbytes for _s, e in self._mesh.values())
                    + sum(e.nbytes for _s, e in self._chunks.values()))
                if len(items) <= 1 or resident <= budget:
                    return
                kind, key, _s, e = min(items, key=lambda it: it[2])
                self._pop_entry_locked(kind, key)
                self._tstats(e.table)[2] += 1

    def shed_coldest(self, frac: float = 0.5) -> int:
        """Memory-pressure relief (exec/shield.py): evict the coldest
        UNPINNED device entries until `frac` of the resident bytes are
        freed, regardless of budget.  Returns bytes freed.  Unlike
        trim() this may evict down to nothing — after a
        RESOURCE_EXHAUSTED the retry restages only what the failed
        dispatch actually needs.  Pinned entries survive: evicting a
        wired chunk/build side would crash the very stream the relief
        is trying to save."""
        freed = 0
        with obs_xray.wait_event("bufpool-evict"), _LOCK:
            resident = (
                sum(e.nbytes for _s, e in self._dev.values())
                + sum(e.nbytes for _s, e in self._mesh.values())
                + sum(e.nbytes for _s, e in self._chunks.values()))
            target = int(resident * max(0.0, min(1.0, frac)))
            while freed < target:
                items = self._evictable_locked()
                if not items:
                    break
                kind, key, _s, e = min(items, key=lambda it: it[2])
                self._pop_entry_locked(kind, key)
                self._tstats(e.table)[2] += 1
                freed += e.nbytes
        return freed

    def _trim_host(self):
        budget = _host_budget()
        with _LOCK:
            while len(self._host) > 1 and \
                    sum(nb for _s, _snap, nb in
                        self._host.values()) > budget:
                key = min(self._host, key=lambda k: self._host[k][0])
                self._host.pop(key)

    # -- invalidation ---------------------------------------------------
    def invalidate(self, store):
        """Eagerly drop every entry backed by this store (DROP TABLE,
        TRUNCATE, vacuum, ALTER fan-out); mesh entries of the same table
        go too — their per-DN version tuple is stale by construction."""
        table = store.td.name
        with _LOCK:
            dropped = self._dev.pop(id(store), None)
            hit = dropped is not None
            if dropped is not None and dropped[1].pins > 0:
                self._orphans.append(dropped[1])
            self._host.pop(id(store), None)
            for key in [k for k, (_s, e) in self._mesh.items()
                        if e.table == table]:
                self._mesh.pop(key)
                hit = True
            for key in [k for k in self._chunks if k[0] == id(store)]:
                _s, e = self._chunks.pop(key)
                # a stream may hold this entry mid-flight: the arrays
                # stay alive through its reference and it unpins through
                # the entry object — track it so the ledger still sees
                # the live pin (check_pin_ledger)
                if e.pins > 0:
                    self._orphans.append(e)
                hit = True
            if hit:
                self._tstats(table)[3] += 1
        # cached RESULTS over this table die with its residency (outside
        # _LOCK: the result cache has its own lock and never calls back
        # into the pool) — DML is caught lazily by the version-tuple
        # mismatch, but DROP/TRUNCATE must reclaim CN memory now
        from ..exec.share import RESULT_CACHE
        RESULT_CACHE.invalidate_table(table)

    # ------------------------------------------------------------------
    # single-device tier (exec/executor.py scans, fused tier, FQS)
    # ------------------------------------------------------------------
    # version-gate: e.version == ver
    def get_device(self, store, colnames):
        """Staged (padded, concatenated) device columns for a store at
        its current version: value columns + MVCC sys columns + null
        masks.  Returns (arrs, n).  Warm path is a dict lookup; version
        drift re-stages — incrementally (tail only) when the store's
        mutation log proves append-only growth."""
        table = store.td.name
        ver = store.version
        nullwant = {_NULL + c for c in colnames
                    if c in store.null_columns}
        want = set(colnames) | set(_SYS_COLS) | nullwant
        with _LOCK:
            ent = self._dev.get(id(store))
            e = ent[1] if ent is not None else None
            if ent is not None:
                ent[0] = next(_SEQ)
            if e is not None and e.version == ver \
                    and want <= set(e.arrs):
                self._tstats(table)[0] += 1
                if obs_trace.ENABLED:
                    obs_trace.event("pool", table=table, hit=True)
                if snapcheck.enabled():
                    snapcheck.serve(
                        "storage.bufferpool.DeviceBufferPool"
                        ".get_device",
                        versions=[(table, e.version)],
                        expect_versions=[(table, ver)])
                return e.arrs, e.n
        obs_trace.event("pool", table=table, hit=False)
        # stage outside the lock (defensive: racing stagers both build,
        # last put wins — same policy as the compiled-program caches)
        stage_span = obs_trace.span("stage", table=table, tier="single")
        with stage_span:
            done = False
            if e is not None and e.version == ver:
                # same version, new columns: keep the resident buffers,
                # stage only what is missing (padded_of skips __enc.*
                # aux arrays — their shapes aren't the row geometry)
                padded = codec.padded_of(e.arrs)
                add, up, aencs = self._stage_columns(
                    store, want - set(e.arrs), e.n, padded)
                arrs = dict(e.arrs)
                arrs.update(add)
                encs = dict(e.encs)
                encs.update(aencs)
                n, tail = e.n, 0
                done = True
            elif e is not None \
                    and store.appended_only_since(e.version, e.n):
                r = self._tail_stage(store, e, want)
                if r is not None:
                    arrs, n, up, tail, encs = r
                    done = True
            if not done:
                # full (re)stage — also the fallback when an encoded
                # column's tail drifted out of its proven range and the
                # descriptor must re-choose (key-visible, like join-
                # ladder growth)
                from .batch import size_class
                n = store.row_count()
                padded = size_class(max(n, 1))
                arrs, up, encs = self._stage_columns(store, want, n,
                                                     padded)
                tail = 0
        stage_span.set(rows=n, tail_rows=tail)
        if up:
            obs_trace.event("upload", table=table, bytes=int(up))
        nbytes = sum(int(a.nbytes) for a in arrs.values())
        codec.note_staged(store, encs)
        with _LOCK:
            st = self._tstats(table)
            st[1] += 1
            if e is not None and e.version != ver and tail == 0:
                st[3] += 1    # stale residency fully replaced
            self.uploaded_bytes += up
            self.tail_rows += tail
            self._dev[id(store)] = [next(_SEQ), DevEntry(
                table, ver, arrs, n, set(store.null_columns), nbytes,
                encs=encs, bytes_logical=codec.logical_nbytes(arrs))]
            self._watch_store(store)
        self.trim()
        return arrs, n

    def _stage_columns(self, store, names, n: int, padded: int):
        """Full staging of rows [0:n] for the given staged-namespace
        names (value columns / __xmin_ts... / __null.c) into padded
        device arrays.  Eligible integer columns stage ENCODED
        (storage/codec.py): the device buffer holds the narrow codes
        and the column's aux array (__enc.*) rides along as a traced
        input.  Returns (arrs, bytes_uploaded, encs)."""
        import jax

        from ..utils.dtypes import stage_cast
        table = store.td.name
        plain = sorted({nm for nm in names if not nm.startswith("__")}
                       | {nm[len(_NULL):] for nm in names
                          if nm.startswith(_NULL)})
        host = store.host_live_columns(plain)
        arrs = {}
        encs = {}
        up = 0
        for name in names:
            h = stage_cast(host[name])
            r = codec.encode_staged(table, name, h[:n])
            if r is not None:
                code, enc, aux = r
                encs[name] = enc
                buf = np.zeros(padded, dtype=code.dtype)
                buf[:n] = code
                arrs[name] = jax.device_put(buf)
                arrs[codec.aux_name(name, enc)] = jax.device_put(aux)
                up += buf.nbytes + aux.nbytes
            else:
                buf = np.zeros((padded, *h.shape[1:]), dtype=h.dtype)
                buf[:n] = h[:n]
                arrs[name] = jax.device_put(buf)
                up += buf.nbytes
        return arrs, up, encs

    def _tail_stage(self, store, e: DevEntry, want):
        """Append-only growth: keep the device prefix, upload only rows
        [e.n:n].  Columns never staged before (or null masks that
        already had prefix NULLs) stage in full; masks whose first NULL
        arrived in the tail get a zeros prefix for free.  Encoded
        columns encode the tail under the entry's EXISTING descriptor
        (resident codes stay valid); a tail outside the proven range
        returns None and the caller takes the full-restage path.
        Dictionary tails may extend the append-only LUT — the aux
        array re-uploads (tiny), the resident codes don't move."""
        import jax
        import jax.numpy as jnp

        from ..utils.dtypes import stage_cast
        from .batch import size_class
        table = store.td.name
        n = store.row_count()
        padded = size_class(max(n, 1))
        aux_keys = set(codec.enc_names(e.arrs).values())
        all_names = (set(e.arrs) - aux_keys) | set(want)
        fresh_nulls = {nm for nm in all_names - set(e.arrs)
                       if nm.startswith(_NULL)
                       and nm[len(_NULL):] not in e.null_at_cache}
        full_names = all_names - set(e.arrs) - fresh_nulls
        plain = sorted({nm for nm in e.arrs if not nm.startswith("__")}
                       | {nm[len(_NULL):] for nm in fresh_nulls})
        tail_host = store.host_live_columns(plain, start=e.n)
        # encode every tail FIRST: a tail outside the proven range
        # PROMOTES that one column (full re-encode under a widened
        # descriptor via the _stage_columns path below) while every
        # other column still takes the tail path — the bounded,
        # key-visible recompile of join-ladder growth, never a full
        # restage of the whole table
        tails = {}
        promote = set()
        if n > e.n:
            for name in e.arrs:
                if name in aux_keys:
                    continue
                t = stage_cast(tail_host[name])
                enc = e.encs.get(name)
                if enc is not None:
                    t = codec.encode_tail(table, name, enc, t)
                    if t is None:
                        promote.add(name)
                        continue
                tails[name] = t
        arrs = {}
        up = 0
        for name, old in e.arrs.items():
            if name in aux_keys or name in promote:
                continue
            if int(old.shape[0]) != padded:
                buf = jnp.zeros((padded, *old.shape[1:]), old.dtype)
                old = buf.at[:e.n].set(old[:e.n])
            t = tails.get(name)
            if t is not None:
                old = old.at[e.n:n].set(jnp.asarray(t))
                up += t.nbytes
            arrs[name] = old
        for name, enc in e.encs.items():
            if name in promote:
                continue     # fresh aux stages with the new descriptor
            akey = codec.aux_name(name, enc)
            if akey not in e.arrs:
                continue
            if enc.family == "dict" and n > e.n:
                aux = codec.aux_host(table, name, enc)
                if aux is None:
                    return None   # ladder moved past the entry
                arrs[akey] = jax.device_put(aux)
                up += aux.nbytes
            else:
                arrs[akey] = e.arrs[akey]
        for name in fresh_nulls:
            buf = jnp.zeros(padded, bool)
            t = tail_host.get(name)
            if t is not None and n > e.n:
                buf = buf.at[e.n:n].set(jnp.asarray(t))
                up += t.nbytes
            arrs[name] = buf
        encs = {k: v for k, v in e.encs.items() if k not in promote}
        if full_names or promote:
            add, up2, aencs = self._stage_columns(
                store, sorted(set(full_names) | promote), n, padded)
            arrs.update(add)
            encs.update(aencs)
            up += up2
        return arrs, n, up, n - e.n, encs

    # ------------------------------------------------------------------
    # morsel chunk tier (exec/morsel.py streaming windows)
    # ------------------------------------------------------------------
    def pin_table(self, store):
        """Pin the store's resident device entry (a streaming join's
        build side must survive per-chunk pressure relief).  Returns
        the DevEntry handle for unpin_table, or None when nothing
        current is resident — the caller stages via get_device first."""
        with _LOCK:
            ent = self._dev.get(id(store))
            if ent is None or ent[1].version != store.version:
                return None
            self._note_pin_locked(ent[1], ent[1].table)
            return ent[1]

    def unpin_table(self, entry: DevEntry):
        with _LOCK:
            self._note_unpin_locked(entry, entry.table)

    # version-gate: ent[1].version == ver
    def get_chunk(self, store, host_cols: dict, start: int,
                  chunk_rows: int, encs: dict = None,
                  consumer=None) -> ChunkEntry:
        """One fixed-shape streaming window of `host_cols` (the staged
        namespace: value columns + MVCC sys columns + null masks),
        staged to device and returned PINNED — the caller unpins via
        unpin_chunk when the window's program call has consumed it.
        device_put is async, so fetching chunk i+1 before blocking on
        chunk i's output double-buffers the host→device copy.  Windows
        are version-keyed like every pool entry; a re-requested warm
        window is a hit (repeat streams over an unchanged table).
        `encs` (from codec.ensure_classes at stream start) encodes the
        window's eligible columns — ensured against the FULL host
        column, so every window of a stream provably shares one
        program class."""
        import jax

        from ..utils.dtypes import stage_cast
        table = store.td.name
        ver = store.version
        # the quantized codec classes are part of the window key: a
        # warm raw window must never alias an encoded stream (mixed
        # avals inside one stream would fork its program class)
        key = (id(store), int(start), int(chunk_rows),
               tuple(sorted(host_cols)),
               tuple(sorted((c, codec.codec_class(en))
                            for c, en in (encs or {}).items())))
        with _LOCK:
            ent = self._chunks.get(key)
            if ent is not None and ent[1].version == ver:
                ent[0] = next(_SEQ)
                self._tstats(table)[0] += 1
                self._note_pin_locked(ent[1], table, consumer)
                if snapcheck.enabled():
                    snapcheck.serve(
                        "storage.bufferpool.DeviceBufferPool"
                        ".get_chunk",
                        versions=[(table, ent[1].version)],
                        expect_versions=[(table, ver)])
                return ent[1]
            if ent is not None:
                self._chunks.pop(key, None)
                if ent[1].pins > 0:
                    self._orphans.append(ent[1])
                self._tstats(table)[3] += 1
        # stage outside the lock (same policy as get_device)
        total = len(next(iter(host_cols.values()))) if host_cols else 0
        live = max(0, min(total, start + chunk_rows) - start)
        arrs = {}
        up = 0
        for name, arr in host_cols.items():
            h = stage_cast(arr)
            r = codec.encode_window(table, name, h[start:start + live]) \
                if (encs and name in encs) else None
            if r is not None:
                code, enc, aux = r
                buf = np.zeros(chunk_rows, dtype=code.dtype)
                if live:
                    buf[:live] = code
                arrs[name] = jax.device_put(buf)
                arrs[codec.aux_name(name, enc)] = jax.device_put(aux)
                up += buf.nbytes + aux.nbytes
            else:
                buf = np.zeros((chunk_rows, *h.shape[1:]),
                               dtype=h.dtype)
                if live:
                    buf[:live] = h[start:start + live]
                arrs[name] = jax.device_put(buf)
                up += buf.nbytes
        e = ChunkEntry(table, ver, int(start), int(chunk_rows),
                       int(live), arrs, up,
                       bytes_logical=codec.logical_nbytes(arrs))
        with _LOCK:
            self._tstats(table)[1] += 1
            self.uploaded_bytes += up
            self._chunks[key] = [next(_SEQ), e]
            self._note_pin_locked(e, table, consumer)
            self._watch_store(store)
        if obs_trace.ENABLED:
            obs_trace.event("chunk_stage", table=table, start=int(start),
                            rows=int(live), bytes=int(up))
        self.trim()
        return e

    def pin_chunk(self, entry: ChunkEntry, consumer=None):
        """Additional per-consumer pin on an already-staged window — a
        shared morsel stream (exec/share.py) fans one leader-staged
        window into every follower, each holding its own refcount."""
        with _LOCK:
            self._note_pin_locked(entry, entry.table, consumer)
        return entry

    def unpin_chunk(self, entry: ChunkEntry, consumer=None):
        with _LOCK:
            self._note_unpin_locked(entry, entry.table, consumer)

    # ------------------------------------------------------------------
    # mesh tier (exec/mesh_exec.py staging)
    # ------------------------------------------------------------------
    def mesh_get(self, runner, table: str, vkey: tuple):
        """Entry for (runner, table) at exactly this per-DN version
        tuple, or None.  Counts the hit/miss; a stale entry counts an
        invalidation but stays resident for mesh_peek's incremental
        tail-patch."""
        with _LOCK:
            ent = self._mesh.get((id(runner), table))
            st = self._tstats(table)
            if ent is not None and ent[1].vkey == vkey:
                ent[0] = next(_SEQ)
                st[0] += 1
                obs_trace.event("pool", table=table, hit=True)
                return ent[1]
            st[1] += 1
            if ent is not None:
                st[3] += 1
            obs_trace.event("pool", table=table, hit=False)
            return None

    def mesh_peek(self, runner, table: str):
        """The resident entry regardless of version (incremental path)."""
        with _LOCK:
            ent = self._mesh.get((id(runner), table))
            return ent[1] if ent is not None else None

    def mesh_put(self, runner, table: str, entry: MeshEntry):
        with _LOCK:
            self._mesh[(id(runner), table)] = [next(_SEQ), entry]
            self._watch_runner(runner)
        self.trim()

    # ------------------------------------------------------------------
    # host snapshots (dn_server stage_table wire op, spill passes)
    # ------------------------------------------------------------------
    # version-gate: store.version == ver
    def host_snapshot(self, store) -> dict:
        """One store's live columns + dictionaries at its current
        version — {"version", "count", "cols", "dicts",
        "null_columns"}.  Version-cached: an unchanged table never
        re-concatenates (the shared staging source for the dn_server
        stage_table op and the mesh runner's in-process snapshots).
        The build re-reads the store version after materializing and
        retries on movement: without the stability loop a DML landing
        mid-concatenation produced a snapshot TAGGED with the old
        version but containing (some of) the new rows — exactly the
        torn entry peek_host_snapshot's version gate cannot catch."""
        snap = self.peek_host_snapshot(store)
        if snap is not None:
            return snap
        while True:
            ver = store.version
            cols = store.host_live_columns([c.name for c in
                                            store.td.columns])
            n = len(next(iter(cols.values()))) if cols \
                else store.row_count()
            snap = {"version": ver, "count": n, "cols": cols,
                    "dicts": {c: list(d.values)
                              for c, d in store.dicts.items()},
                    "null_columns": set(store.null_columns)}
            if store.version == ver:
                break
        if snapcheck.enabled():
            snapcheck.serve(
                "storage.bufferpool.DeviceBufferPool.host_snapshot",
                versions=[(store.td.name, snap["version"])],
                expect_versions=[(store.td.name, ver)])
        nbytes = sum(int(a.nbytes) for a in cols.values())
        if nbytes <= _host_budget():
            with _LOCK:
                self._host[id(store)] = [next(_SEQ), snap, nbytes]
                self._watch_store(store)
            self._trim_host()
        return snap

    def resident(self, store) -> bool:
        """Does this store have a device entry at its CURRENT version?
        (warm-start assertions, tests)."""
        with _LOCK:
            ent = self._dev.get(id(store))
            return ent is not None and ent[1].version == store.version

    # version-gate: ent[1]["version"] == ver
    def peek_host_snapshot(self, store):
        """The cached host snapshot IF current, else None (never
        builds) — spill passes reuse it instead of re-concatenating."""
        with _LOCK:
            ent = self._host.get(id(store))
            ver = store.version
            if ent is not None and ent[1]["version"] == ver:
                ent[0] = next(_SEQ)
                if snapcheck.enabled():
                    snapcheck.serve(
                        "storage.bufferpool.DeviceBufferPool"
                        ".peek_host_snapshot",
                        versions=[(store.td.name,
                                   ent[1]["version"])],
                        expect_versions=[(store.td.name, ver)])
                return ent[1]
        return None


#: process-global pool — every LocalNode / DataNode / MeshRunner in the
#: process shares one budget (entries are keyed by store identity, so
#: nodes never alias each other's tables)
POOL = DeviceBufferPool()


def _metrics_samples():
    """Registry collector: pool totals as samples (obs/metrics.py)."""
    for k, v in POOL.totals().items():
        yield (f"otb_buffercache_{k}", {}, v)


from ..obs.metrics import REGISTRY as _METRICS  # noqa: E402
_METRICS.register_collector("bufferpool", _metrics_samples)
