"""Columnar codecs: compressed device residency for staged columns.

Reference analog: TOAST / varlena compression (src/backend/access/
common/toast_internals.c) — the reference compresses wide values so a
heap page holds more rows and the buffer cache goes further.  Here the
scarce cache is device HBM and the dominant cost is host->device
transfer (the PR-12 morsel bench made PCIe the critical path), so the
compression unit is the COLUMN: every staged device array carries the
narrowest integer representation its values provably fit, and the
executor computes on the codes — decode is an elementwise affine map /
LUT gather that XLA fuses into the consuming kernel, so most payload
columns never materialize decoded.

Three codec families, chosen per column at stage time from the actual
values, persisted like the join ladder (exec/fused.py _JOIN_LADDER):

- pack (uint8/16/32): direct downcast, proven 0 <= v <= 2^w - 1.
  Zero-padding decodes to 0 exactly (matches raw staging).
- for (frame-of-reference, uint8/16/32): code = v - lo + 1 with the
  reference `lo` from the proven min.  Code 0 is RESERVED as the
  padding sentinel so zero-padded rows decode to exactly 0 — MVCC
  visibility (ops/kernels.py visibility_mask) depends on padded
  __xmax_ts staying 0.  The reference rides the staged dict as a
  shape-(1,) aux array (`__enc.for.<col>`, value lo - 1), a TRACED
  input: reference drift never recompiles.
- dict (uint8/16): append-only dictionary for low-cardinality ints —
  the TEXT union-dictionary scheme (storage/store.py StringDict)
  extended to integers.  Codes are index + 1; slot 0 of the LUT is the
  0 sentinel for padding.  The LUT is a pow2-capacity aux array
  (`__enc.dict.<col>`), traced, so append-only growth within capacity
  never changes a program.

Program-key discipline (analysis/cardinality.py codec-key rule): the
only encoding-derived value that may reach program-key material is the
quantized class token from codec_class() — family + width (+ pow2 LUT
capacity), e.g. "pack8", "for16", "dict8/256".  Widths are an enum,
capacities quantize through batch.lut_capacity, so the key domain
stays bounded and otbcard's cardinality proof holds.  Aux CONTENTS
(references, LUT values) are traced data, never key material.

The per-(table, column) descriptor ladder is process-global so every
holder of a table — primary store, HotStandby replica store, mesh
shards — encodes with one descriptor and dictionary codes stay valid
across replicas.  A value outside the proven range re-chooses the
descriptor (monotone widening), which is key-visible and costs one
bounded recompile, exactly like join-ladder growth.  Set
OTB_CODEC_STATE=<path> to persist the ladder to a JSON file across
processes (documented in README next to the join-ladder docs);
OTB_CODEC=0 disables encoding entirely (bit-identity escape hatch).
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from ..utils import locks
from .batch import lut_capacity

#: staged-namespace prefix for codec aux arrays: FOR references and
#: dictionary LUTs ride the staged dict as traced program inputs — the
#: CLASS is program-key material, the aux contents never are.
ENC_PREFIX = "__enc."

_STATE_LOCK = locks.RLock("storage.codec._STATE_LOCK")
_WIDTHS = (8, 16, 32)
_DICT_SAMPLE = 1 << 16    # probe rows before an exact unique() pass
_DICT_MAX_CARD = 1 << 12  # beyond this, dictionary residency stops paying


@dataclasses.dataclass(frozen=True)
class Enc:
    """One column's encoding descriptor.  family/width/cap are the
    QUANTIZED key material (codec_class); `lo` and the dictionary
    values are data, shipped through traced aux arrays."""
    family: str   # "pack" | "for" | "dict"
    width: int    # 8 | 16 | 32 — code dtype is uint{width}
    orig: str     # original staged dtype str ("int64", "int32", ...)
    lo: int = 0   # for: reference (code = v - lo + 1; 0 = padding)
    cap: int = 0  # dict: pow2 LUT capacity incl the sentinel slot

    @property
    def code_dtype(self):
        return np.dtype(f"uint{self.width}")


class _ColState:
    """Ladder entry for one (table, column): the persisted descriptor
    plus append-only dictionary state.  guarded_by: _STATE_LOCK"""
    __slots__ = ("enc", "values", "index")

    def __init__(self, enc, values=None):
        self.enc = enc                    # Enc | None (None = raw pin)
        self.values = list(values or [])  # dict family: code-1 -> value
        self.index = {v: i + 1 for i, v in enumerate(self.values)}


#: (table, col) -> _ColState
_LADDER: dict = {}     # guarded_by: _STATE_LOCK
_STATE_LOADED = False  # guarded_by: _STATE_LOCK


def enabled() -> bool:
    """Codec escape hatch: OTB_CODEC=0 stages every column raw (the
    bit-identity A/B arm in bench.py and tests/test_codec.py)."""
    return os.environ.get("OTB_CODEC", "1") != "0"


def eligible(name: str, h) -> bool:
    """Encodable staged arrays: 1-D integers wider than a byte — value
    columns, MVCC sys columns, TEXT dict codes.  Null masks (bool),
    floats and vector payloads stage raw."""
    return (not name.startswith(ENC_PREFIX)
            and h.ndim == 1 and h.dtype.kind in "iu"
            and h.dtype.itemsize > 1)


# -- quantized key material ---------------------------------------------
def codec_class(enc) -> str:
    """The quantized codec-class token — the ONLY encoding-derived
    value allowed into program-key material (the codec-key lint rule):
    family + width, plus the pow2 LUT capacity for dictionaries (the
    capacity is the aux array's shape, hence aval-visible, hence it
    must be key-visible; it is already quantized via lut_capacity)."""
    if enc is None:
        return "raw"
    if enc.family == "dict":
        return f"dict{enc.width}/{enc.cap}"
    return f"{enc.family}{enc.width}"


def codec_classes(store) -> tuple:
    """The codec classes actually STAGED for this store, sorted —
    program-key material for the fused tier (exec/fused.py
    _table_sig).  Reads what note_staged recorded at staging time, not
    the live ladder, so key and traced avals can never disagree when
    another holder of the same table name promotes the ladder."""
    return tuple(sorted(getattr(store, "_otb_codec_classes", {}).items()))


def note_staged(store, encs: dict) -> None:
    """Record the classes staged for this store (bufferpool staging /
    morsel ensure_classes) — the source codec_classes() reads."""
    try:
        store._otb_codec_classes = {
            c: codec_class(e) for c, e in encs.items() if e is not None}
    except AttributeError:
        pass


def invalidate_ladder(table: str) -> None:
    """Drop a table's ladder entries (the DDL-drop invalidation edge:
    a re-created table must re-learn its descriptors, not inherit the
    dead table's value distribution)."""
    with _STATE_LOCK:
        for key in [k for k in _LADDER if k[0] == table]:
            del _LADDER[key]
        _save_locked()


# -- descriptor choice / validation -------------------------------------
def _range_width(span: int):
    """Narrowest enum width whose code space holds `span` values plus
    the padding sentinel."""
    for w in _WIDTHS:
        if span <= (1 << w) - 2:
            return w
    return None


def _fits_locked(st: _ColState, h) -> bool:
    """Do these values fit the persisted descriptor without widening?
    (Dictionaries may still extend append-only within capacity.)"""
    enc = st.enc
    if str(h.dtype) != enc.orig:
        return False
    if h.size == 0:
        return True
    vmin, vmax = int(h.min()), int(h.max())
    if enc.family == "pack":
        return vmin >= 0 and vmax <= (1 << enc.width) - 1
    if enc.family == "for":
        return vmin >= enc.lo and vmax - enc.lo <= (1 << enc.width) - 2
    u = np.unique(h)
    new = sum(1 for v in u if int(v) not in st.index)
    return len(st.values) + new + 1 <= enc.cap


def _choose_locked(h, prev=None) -> _ColState:
    """Choose a descriptor from the actual values.  `prev` is the
    outgrown state, if any — an outgrown DICTIONARY extends its
    append-only value list into a larger capacity (codes already
    resident elsewhere stay valid) instead of rebuilding."""
    orig = str(h.dtype)
    if h.size == 0:
        # nothing provable yet: stage raw WITHOUT pinning, so the
        # first real load still gets to choose
        return _ColState(None)
    vmin, vmax = int(h.min()), int(h.max())
    itemsize = h.dtype.itemsize

    if prev is not None and prev.enc is not None \
            and prev.enc.family == "dict":
        u = np.unique(h)
        new = [int(v) for v in u if int(v) not in prev.index]
        nvals = len(prev.values) + len(new)
        if nvals <= _DICT_MAX_CARD:
            cap, width = _dict_geometry(nvals)
            if width is not None and width // 8 < itemsize:
                st = _ColState(
                    Enc("dict", width, orig, cap=cap), prev.values)
                for v in new:
                    st.index[v] = len(st.values) + 1
                    st.values.append(v)
                return st

    pack_w = _range_width(vmax) if vmin >= 0 else None
    for_w = None
    if vmin > np.iinfo(h.dtype).min:  # lo - 1 must be representable
        for_w = _range_width(vmax - vmin)
        if for_w is not None and vmin >= (1 << 40):
            # wall-clock-scale reference (MVCC timestamps): appends
            # drift forward forever, so a width proven on today's span
            # would promote on every batch — start at 32 bits (still
            # 2x narrower than the int64 original)
            for_w = max(for_w, 32)
    best = None
    for fam, w in (("pack", pack_w), ("for", for_w)):
        if w is not None and w // 8 < itemsize \
                and (best is None or w < best[1]):
            best = (fam, w)

    if best is None or best[1] > 8:
        st = _dict_choose(h, itemsize, orig,
                          best[1] if best else 8 * itemsize)
        if st is not None:
            return st
    if best is None:
        return _ColState(None)
    fam, w = best
    lo = vmin if fam == "for" else 0
    return _ColState(Enc(fam, w, orig, lo=lo))


def _dict_geometry(nvals: int):
    """(cap, width) for a dictionary of `nvals` values: pow2 capacity
    with headroom, clamped to the width's code space."""
    width = 8 if nvals + 1 <= (1 << 8) else 16
    if nvals + 1 > (1 << 16):
        return 0, None
    cap = min(lut_capacity(nvals + 1 + (nvals >> 2) + 1), 1 << width)
    return cap, width


def _dict_choose(h, itemsize: int, orig: str, beat_width: int):
    """Try the dictionary family: cheap sample probe first, exact
    unique() only when the sample looks low-cardinality."""
    sample = h if h.size <= _DICT_SAMPLE \
        else h[::max(1, h.size // _DICT_SAMPLE)]
    if np.unique(sample).size > _DICT_MAX_CARD:
        return None
    u = np.unique(h)
    if u.size > _DICT_MAX_CARD:
        return None
    cap, width = _dict_geometry(int(u.size))
    if width is None or width >= beat_width or width // 8 >= itemsize:
        return None
    return _ColState(Enc("dict", width, orig, cap=cap),
                     [int(v) for v in u])


# -- encode --------------------------------------------------------------
def _encode_locked(st: _ColState, h):
    """Encode under the existing descriptor, or None on a range/dtype
    violation.  Dictionary encode extends the append-only LUT within
    capacity (the caller re-uploads the aux array afterwards)."""
    enc = st.enc
    if str(h.dtype) != enc.orig:
        return None
    if h.size == 0:
        return np.zeros(0, enc.code_dtype)
    vmin, vmax = int(h.min()), int(h.max())
    if enc.family == "pack":
        if vmin < 0 or vmax > (1 << enc.width) - 1:
            return None
        return h.astype(enc.code_dtype)
    if enc.family == "for":
        if vmin < enc.lo or vmax - enc.lo > (1 << enc.width) - 2:
            return None
        return (h.astype(np.int64)
                - np.int64(enc.lo - 1)).astype(enc.code_dtype)
    u, inv = np.unique(h, return_inverse=True)
    new = [int(v) for v in u if int(v) not in st.index]
    if len(st.values) + len(new) + 1 > enc.cap:
        return None
    changed = bool(new)
    for v in new:
        st.index[v] = len(st.values) + 1
        st.values.append(v)
    if changed:
        _save_locked()
    ucodes = np.asarray([st.index[int(v)] for v in u],
                        dtype=enc.code_dtype)
    return ucodes[np.asarray(inv)]


def encode_staged(table: str, name: str, h):
    """Validate-or-choose the persisted descriptor for this column
    against the full staged values and encode.  Returns
    (codes, enc, aux_host) or None to stage raw.  A misfit (append
    drifted out of the proven range) re-chooses and persists — a
    key-visible, bounded recompile, exactly like join-ladder growth."""
    if not enabled() or not eligible(name, h):
        return None
    h = np.ascontiguousarray(h)
    with _STATE_LOCK:
        _load_locked()
        key = (table, name)
        st = _LADDER.get(key)
        if st is not None and st.enc is None:
            return None               # proven-raw pin: stays raw
        codes = _encode_locked(st, h) if st is not None else None
        if codes is None:
            st = _choose_locked(h, prev=st)
            _LADDER[key] = st
            _save_locked()
            if st.enc is None:
                return None
            codes = _encode_locked(st, h)
            assert codes is not None, (table, name, st.enc)
        return codes, st.enc, _aux_locked(st)


def encode_tail(table: str, name: str, enc: Enc, t):
    """Encode an append tail under an entry's EXISTING descriptor —
    never chooses or promotes.  Returns codes, or None when the tail
    drifted out of range (or the ladder moved past the entry): the
    caller falls back to a full restage.  Dictionary tails may extend
    the append-only LUT within capacity; the caller re-uploads the aux
    array (aux_host) after a successful tail encode."""
    with _STATE_LOCK:
        st = _LADDER.get((table, name))
        if st is None or st.enc != enc:
            return None
        return _encode_locked(st, np.ascontiguousarray(t))


def encode_window(table: str, name: str, h):
    """Encode one morsel window under the ladder descriptor ensured at
    stream start (ensure_classes) — validate-only, never chooses, so
    every chunk of a stream provably shares ONE program class.
    Returns (codes, enc, aux_host) or None (stage raw)."""
    if not enabled() or not eligible(name, h):
        return None
    with _STATE_LOCK:
        st = _LADDER.get((table, name))
        if st is None or st.enc is None:
            return None
        codes = _encode_locked(st, np.ascontiguousarray(h))
        if codes is None:
            return None
        return codes, st.enc, _aux_locked(st)


def ensure_classes(store, host_cols: dict) -> dict:
    """Stream-start ensure: validate-or-choose descriptors for every
    eligible staged column from the FULL host values, so each window
    of the stream (encode_window) fits one descriptor and the chunk
    programs never fork classes mid-stream.  Records the result on the
    store for codec_classes (program-key material).  Returns
    {col: Enc} for the encoded columns."""
    from ..utils.dtypes import stage_cast
    table = store.td.name
    encs: dict = {}
    if enabled():
        with _STATE_LOCK:
            _load_locked()
            for name in sorted(host_cols):
                h = stage_cast(np.asarray(host_cols[name]))
                if not eligible(name, h):
                    continue
                key = (table, name)
                st = _LADDER.get(key)
                if st is None or (st.enc is not None
                                  and not _fits_locked(st, h)):
                    st = _choose_locked(h, prev=st)
                    _LADDER[key] = st
                    _save_locked()
                if st.enc is not None:
                    encs[name] = st.enc
    note_staged(store, encs)
    return encs


# -- aux arrays ----------------------------------------------------------
def aux_name(name: str, enc: Enc) -> str:
    """Staged-dict key of a column's aux array; the FAMILY rides the
    name so a staged dict is self-describing (enc_names)."""
    return f"{ENC_PREFIX}{enc.family}.{name}"


def _aux_locked(st: _ColState) -> np.ndarray:
    enc = st.enc
    od = np.dtype(enc.orig)
    if enc.family == "pack":
        # dtype marker only: decode target dtype = aux dtype
        return np.zeros(1, od)
    if enc.family == "for":
        return np.asarray([enc.lo - 1], od)
    lut = np.zeros(enc.cap, od)
    if st.values:
        lut[1:1 + len(st.values)] = np.asarray(st.values, od)
    return lut


def aux_host(table: str, name: str, enc: Enc):
    """Current host aux array for an encoded column (fresh LUT after a
    tail-extend), or None if the ladder moved past `enc`."""
    with _STATE_LOCK:
        st = _LADDER.get((table, name))
        if st is None or st.enc != enc:
            return None
        return _aux_locked(st)


# -- staged-dict introspection ------------------------------------------
def enc_names(arrs: dict) -> dict:
    """{col: aux_key} for every encoded column of a staged dict."""
    out = {}
    for k in arrs:
        if k.startswith(ENC_PREFIX):
            _fam, col = k[len(ENC_PREFIX):].split(".", 1)
            out[col] = k
    return out


def family_of(aux_key: str) -> str:
    return aux_key[len(ENC_PREFIX):].split(".", 1)[0]


def padded_of(arrs: dict) -> int:
    """Padded row count of a staged dict, skipping aux arrays (aux
    shapes are (1,) / (cap,), not the padded row geometry)."""
    for k, a in arrs.items():
        if not k.startswith(ENC_PREFIX):
            return int(a.shape[0])
    return 0


def logical_nbytes(arrs: dict) -> int:
    """Bytes this staged dict would occupy UNENCODED (original
    dtypes) — the numerator of otb_buffercache's effective-cache
    ratio (bytes_logical / bytes_resident)."""
    aux = enc_names(arrs)
    total = 0
    for k, a in arrs.items():
        if k.startswith(ENC_PREFIX):
            continue
        if k in aux:
            n = 1
            for d in a.shape:
                n *= int(d)
            total += n * int(np.dtype(arrs[aux[k]].dtype).itemsize)
        else:
            total += int(a.nbytes)
    return total


# -- ladder persistence --------------------------------------------------
def _state_path():
    return os.environ.get("OTB_CODEC_STATE") or None


def _load_locked():  # holds: _STATE_LOCK
    global _STATE_LOADED
    if _STATE_LOADED:
        return
    _STATE_LOADED = True
    path = _state_path()
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return
    for d in data:
        key = (d["table"], d["col"])
        if d["family"] == "raw":
            _LADDER[key] = _ColState(None)
        else:
            enc = Enc(d["family"], int(d["width"]), d["orig"],
                      lo=int(d.get("lo", 0)), cap=int(d.get("cap", 0)))
            _LADDER[key] = _ColState(enc, d.get("values"))


def _save_locked():
    path = _state_path()
    if not path:
        return
    out = []
    for (table, col), st in sorted(_LADDER.items()):
        d = {"table": table, "col": col}
        if st.enc is None:
            d["family"] = "raw"
        else:
            d.update(family=st.enc.family, width=st.enc.width,
                     orig=st.enc.orig, lo=st.enc.lo, cap=st.enc.cap)
            if st.enc.family == "dict":
                d["values"] = list(st.values)
        out.append(d)
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, path)
    except OSError:
        pass


def ladder_snapshot() -> list:
    """(table, col, class) rows — obs / tests."""
    with _STATE_LOCK:
        return [(t, c, codec_class(st.enc))
                for (t, c), st in sorted(_LADDER.items())]


def reset_state():
    """Drop the descriptor ladder (tests / bench arm isolation)."""
    global _STATE_LOADED
    with _STATE_LOCK:
        _LADDER.clear()
        _STATE_LOADED = False
