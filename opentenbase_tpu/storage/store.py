"""Columnar shard store — the datanode's table storage.

Reference analog: heap storage (src/backend/access/heap) + buffer manager
(src/backend/storage/buffer).  Re-designed columnar/TPU-first:

- A table on a datanode is a list of fixed-capacity columnar Chunks
  (column arrays in host RAM; device HBM is a staging cache, never the
  source of truth — SURVEY.md §7.1).
- MVCC lives in four per-row int64/int32 columns: xmin_ts / xmax_ts
  (commit GTS of creator/deleter — the reference embeds exactly these two
  8-byte GTS fields in every heap tuple header,
  include/access/htup_details.h:126-144) and xmin_txid / xmax_txid for
  in-progress/own-transaction checks.  Visibility is a vector compare
  (reference: per-tuple HeapTupleSatisfiesMVCC, utils/time/tqual.c:1203).
- Every row stores its shard id (reference: HeapTupleHeader t_shardid,
  htup_details.h:191; extents are shard-pure, extentmapping.h:129).
- TEXT columns are dictionary-encoded per store; the dictionary maps
  code -> str and is node-local (joins are never on raw strings; group-by
  results are decoded before crossing nodes).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterator, Optional

import numpy as np

from ..catalog.schema import TableDef
from ..catalog.types import TypeKind
from ..utils import locks

INF_TS = np.int64(1 << 62)        # "not yet deleted" / "not yet committed"
ABORTED_TS = np.int64((1 << 62) + 1)  # creator aborted: never visible
NO_TXID = np.int64(0)

CHUNK_CAP = 1 << 16


def _decimal_str(v: int, scale: int) -> str:
    """Storage-scaled int -> exact decimal string ('-3.25' for -325/2)."""
    if scale == 0:
        return str(v)
    sign = "-" if v < 0 else ""
    a = abs(v)
    return f"{sign}{a // 10 ** scale}.{a % 10 ** scale:0{scale}d}"


class WriteConflict(Exception):
    """Concurrent write-write conflict.  Carries the holding txid so the
    datanode's lock manager can wait for it (reference: the updater xid
    a blocked heap_update waits on, XactLockTableWait)."""

    def __init__(self, msg: str, holder: int = 0):
        super().__init__(msg)
        self.holder = int(holder)


class SerializationConflict(Exception):
    """The row version this txn targeted was replaced by a COMMITTED
    concurrent writer (reference: 'could not serialize access due to
    concurrent update').  Implicit single-statement transactions retry
    with a fresh snapshot; explicit transactions surface the error."""


import itertools as _itertools

# process-global version source: values never repeat across stores, so a
# device-cache entry keyed by a recycled id(store) can never alias a new
# store's version
_VERSION_COUNTER = _itertools.count(1)


class StringDict:
    """Append-only code<->string dictionary for one TEXT column."""

    def __init__(self):
        self.values: list[str] = []
        self._index: dict[str, int] = {}

    def encode_one(self, s: str) -> int:
        code = self._index.get(s)
        if code is None:
            code = len(self.values)
            self.values.append(s)
            self._index[s] = code
        return code

    def encode(self, strings) -> np.ndarray:
        return np.fromiter((self.encode_one(s) for s in strings),
                           dtype=np.int32, count=len(strings))

    def encode_array(self, arr: np.ndarray) -> np.ndarray:
        """Vectorized encode for numpy string/bytes arrays: unique once
        (C speed), register only the uniques, map back by inverse."""
        uniq, inv = np.unique(arr, return_inverse=True)
        base = np.empty(len(uniq), dtype=np.int32)
        for i, u in enumerate(uniq):
            s = u.decode("utf-8", "replace") if isinstance(u, bytes) \
                else str(u)
            base[i] = self.encode_one(s)
        return base[inv.reshape(-1)].astype(np.int32)

    def decode(self, codes: np.ndarray) -> list[str]:
        return [self.values[int(c)] for c in codes]

    def codes_matching(self, pred) -> np.ndarray:
        """All codes whose string satisfies `pred` — string predicates are
        evaluated once against the dictionary, then become device-side code
        membership masks."""
        return np.asarray([i for i, v in enumerate(self.values) if pred(v)],
                          dtype=np.int32)


@dataclasses.dataclass
class Chunk:
    columns: dict[str, np.ndarray]
    xmin_ts: np.ndarray
    xmax_ts: np.ndarray
    xmin_txid: np.ndarray
    xmax_txid: np.ndarray
    shardid: np.ndarray
    nrows: int
    cap: int
    # per-column null bitmaps, allocated lazily on the first NULL
    # (reference: the per-tuple null bitmap in HeapTupleHeader,
    # include/access/htup_details.h t_bits)
    nulls: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    # row locks (SELECT FOR UPDATE), allocated lazily — transient, not
    # checkpointed/WAL-logged: a crash aborts every holder anyway
    # (reference: xmax LOCK_ONLY infomask bits, heapam.c)
    lock_txid: np.ndarray = None

    def lock_array(self) -> np.ndarray:
        if self.lock_txid is None:
            self.lock_txid = np.full(self.cap, NO_TXID, dtype=np.int64)
        return self.lock_txid

    @staticmethod
    def empty(td: TableDef, cap: int = CHUNK_CAP) -> "Chunk":
        cols = {c.name: np.empty((cap, *c.type.shape_suffix),
                                 dtype=c.type.np_dtype)
                for c in td.columns}
        return Chunk(
            columns=cols,
            xmin_ts=np.empty(cap, dtype=np.int64),
            xmax_ts=np.empty(cap, dtype=np.int64),
            xmin_txid=np.empty(cap, dtype=np.int64),
            xmax_txid=np.empty(cap, dtype=np.int64),
            shardid=np.empty(cap, dtype=np.int32),
            nrows=0, cap=cap)

    def null_mask_for(self, name: str) -> np.ndarray:
        """The column's null bitmap, allocating a cleared one on demand."""
        m = self.nulls.get(name)
        if m is None:
            m = self.nulls[name] = np.zeros(len(self.columns[name]),
                                            dtype=bool)
        return m

    @property
    def free(self) -> int:
        return self.cap - self.nrows


# "no existing row touched" marker for the mutation log (pure append)
NO_ROW = 1 << 62


class TableStore:
    """All chunks of one table on one datanode."""

    def __init__(self, td: TableDef):
        self.td = td
        self.chunks: list[Chunk] = []
        # serializes check-then-set row marking and chunk appends: DN
        # host ops run concurrently across sessions (the reference gets
        # per-tuple atomicity from buffer-page locks, bufmgr.c)
        self._mu = locks.RLock("storage.store.TableStore._mu")
        self.version = next(_VERSION_COUNTER)  # bumped on any mutation
        # prefix-mutation log: (version, lowest scan-order row touched)
        # for every mutation that rewrote EXISTING rows.  The device
        # buffer pool replays it to prove a cached snapshot's prefix is
        # still byte-exact (no entry past the cached version touches a
        # row below the cached count) and stage just the appended tail
        # (storage/bufferpool.py).  Pure tail appends are never logged —
        # they cannot invalidate any earlier prefix — so arbitrarily
        # long append bursts stay provable; _trim_floor marks how far
        # back the bounded log still covers, and the row high-water mark
        # forces logging of appends that follow a shrink (truncate/
        # vacuum), whose base may undercut an older snapshot's count.
        self._dirty_log: list[tuple[int, int]] = []
        self._trim_floor = 0
        self._rows_high_water = 0
        self.dicts: dict[str, StringDict] = {
            c.name: StringDict() for c in td.columns
            if c.type.kind == TypeKind.TEXT}
        # columns that hold at least one NULL anywhere (drives null-mask
        # staging into the device cache; empty for NOT NULL workloads)
        self.null_columns: set[str] = set()
        # ANN indexes over VECTOR columns: col -> {"centroids", "metric",
        # "nprobe", "_assign_cache"} (contrib/pgvector IVFFlat analog)
        self.ann_indexes: dict[str, dict] = {}
        # btree-equivalent indexes: col -> {"keys": sorted values,
        # "pos": live-row positions, "version": built-at store version}
        # (reference: nbtree — here a sorted array + binary search, the
        # pointer-free TPU-era shape of the same idea)
        self.btree_indexes: dict[str, dict] = {}

    # ------------------------------------------------------------------
    def _note_mutation(self, min_row: int) -> None:
        """Bump the store version; log the mutation when it could
        invalidate some snapshot's prefix (it touched a row below the
        high-water row count — pure appends at the current tail never
        do, so they stay unlogged and cost O(1))."""
        self.version = next(_VERSION_COUNTER)
        hw = max(self._rows_high_water, self.row_count())
        if min_row < hw:
            self._dirty_log.append((self.version, int(min_row)))
            if len(self._dirty_log) > 128:
                drop = len(self._dirty_log) - 128
                self._trim_floor = self._dirty_log[drop - 1][0]
                del self._dirty_log[:drop]
        self._rows_high_water = hw

    def _chunk_start(self, ci: int) -> int:
        """Scan-order position of chunk `ci`'s first row.  Stable under
        append-only history (inserts only extend the last chunk / append
        new ones); the ops that DO shift it (vacuum, truncate) log
        min_row=0 and force a full restage anyway."""
        return sum(c.nrows for c in self.chunks[:ci])

    def _spans_min_row(self, spans) -> int:
        """Lowest scan-order row in a backfill span list [(ci, lo, hi)]."""
        m = NO_ROW
        for ci, lo, _hi in spans:
            m = min(m, self._chunk_start(ci) + lo)
        return m

    def _idx_spans_min_row(self, spans) -> int:
        """Lowest scan-order row in a delete span list [(ci, idx)]."""
        m = NO_ROW
        for ci, idx in spans:
            if len(idx):
                m = min(m, self._chunk_start(ci) + int(idx.min()))
        return m

    def appended_only_since(self, version: int, nrows: int) -> bool:
        """True when every mutation after `version` touched only rows
        at scan positions >= nrows — i.e. a snapshot of the first
        `nrows` rows taken at `version` is still byte-exact and only
        the tail needs (re)staging.  Conservative: returns False when
        the bounded log no longer covers the gap (prefix entries were
        trimmed past the asked-for version)."""
        if self.version == version:
            return True
        if version < self._trim_floor:
            return False      # entries in the gap may have been dropped
        for v, r in self._dirty_log:
            if v > version and r < nrows:
                return False
        return True

    def row_count(self) -> int:
        return sum(c.nrows for c in self.chunks)

    def split_nulls(self, name: str, values):
        """Split python None entries out of a raw value sequence:
        returns (clean_values, mask|None).  NULL positions take a
        DETERMINISTIC type-default fill (""/0/epoch) — never a value from
        the batch — so NULL distribution-key rows always route to the
        same shard regardless of batch contents (matches the
        dist_session routing fill)."""
        if isinstance(values, np.ndarray) and values.dtype.kind != "O":
            return values, None
        mask = np.fromiter((v is None for v in values), dtype=bool,
                           count=len(values))
        if not mask.any():
            return values, None
        ct = self.td.column(name).type
        k = ct.kind
        if k == TypeKind.TEXT:
            fill = ""
        elif k == TypeKind.VECTOR:
            fill = [0.0] * ct.dim
        elif k == TypeKind.DATE and any(
                isinstance(v, str) for v in values if v is not None):
            fill = "1970-01-01"  # string-modal date batch: epoch string
        else:
            fill = 0
        clean = [fill if v is None else v for v in values]
        return clean, mask

    def encode_column(self, name: str, values) -> np.ndarray:
        """Convert python/raw values into the stored array representation."""
        col = self.td.column(name)
        k = col.type.kind
        if k == TypeKind.TEXT:
            if isinstance(values, np.ndarray) and values.dtype.kind in "SU":
                return self.dicts[name].encode_array(values)
            return self.dicts[name].encode([str(v) for v in values])
        arr = np.asarray(values)
        if k == TypeKind.DECIMAL:
            from .loader import _PreScaled
            if isinstance(values, _PreScaled):
                return np.asarray(values).astype(np.int64)
            scale = col.type.scale
            if arr.dtype.kind in "iu":
                return arr.astype(np.int64) * np.int64(10 ** scale)
            if arr.dtype.kind == "f":
                return np.round(arr * 10 ** scale).astype(np.int64)
            from ..catalog.types import decimal_to_int
            return np.asarray([decimal_to_int(v, scale)
                               for v in values], dtype=np.int64)
        if k == TypeKind.DATE and arr.dtype.kind in "UO":
            from ..catalog.types import date_to_days
            return np.asarray([date_to_days(str(v)) for v in values],
                              dtype=np.int32)
        if k == TypeKind.VECTOR:
            if arr.dtype.kind in "UO":
                # pgvector text form: '[1,2,3]'
                arr = np.asarray([
                    np.array(str(v).strip().strip("[]").split(","),
                             dtype=np.float32)
                    if isinstance(v, str) else np.asarray(v, np.float32)
                    for v in values])
            arr = arr.astype(np.float32)
            if arr.ndim != 2 or arr.shape[1] != col.type.dim:
                raise ValueError(
                    f"vector column {name!r} expects dim {col.type.dim}")
            return arr
        return arr.astype(col.type.np_dtype)

    def insert(self, columns: dict[str, np.ndarray], nrows: int,
               txid: int, shardids: Optional[np.ndarray] = None,
               commit_ts: Optional[int] = None,
               nulls: Optional[dict[str, np.ndarray]] = None
               ) -> list[tuple[int, int, int]]:
        """Append rows (already encoded).  Returns [(chunk_idx, start, end)]
        spans for the transaction's backfill list.  If commit_ts is given the
        rows are born committed (bulk load fast path, like the reference's
        COPY FREEZE).  `nulls` maps column -> bool mask of NULL positions
        (value arrays hold type-default fill there)."""
        if nrows == 0:
            return []
        self._mu.acquire()
        try:
            return self._insert_locked(columns, nrows, txid, shardids,
                                       commit_ts, nulls)
        finally:
            self._mu.release()

    def _insert_locked(self, columns, nrows, txid, shardids,
                       commit_ts, nulls):
        # pure append: the lowest affected row is where the new rows
        # begin (nothing before it changes)
        self._note_mutation(self.row_count())
        spans = []
        done = 0
        born_ts = INF_TS if commit_ts is None else np.int64(commit_ts)
        live_nulls = {n: m for n, m in (nulls or {}).items()
                      if np.any(m)}
        self.null_columns |= set(live_nulls)
        while done < nrows:
            if not self.chunks or self.chunks[-1].free == 0:
                self.chunks.append(Chunk.empty(self.td, CHUNK_CAP))
            ch = self.chunks[-1]
            take = min(ch.free, nrows - done)
            lo, hi = ch.nrows, ch.nrows + take
            for name, arr in columns.items():
                ch.columns[name][lo:hi] = arr[done:done + take]
            for name, m in live_nulls.items():
                ch.null_mask_for(name)[lo:hi] = m[done:done + take]
            for name in ch.nulls:
                # a chunk that already tracks nulls for a column must
                # clear the bits for rows inserted without nulls
                if name not in live_nulls:
                    ch.nulls[name][lo:hi] = False
            ch.xmin_ts[lo:hi] = born_ts
            ch.xmax_ts[lo:hi] = INF_TS
            ch.xmin_txid[lo:hi] = txid
            ch.xmax_txid[lo:hi] = NO_TXID
            ch.shardid[lo:hi] = (shardids[done:done + take]
                                 if shardids is not None else -1)
            ch.nrows = hi
            spans.append((len(self.chunks) - 1, lo, hi))
            done += take
        return spans

    def mark_delete(self, chunk_idx: int, row_mask: np.ndarray,
                    txid: int) -> tuple[int, np.ndarray]:
        """Stamp xmax_txid for rows being deleted by txn (pending until
        commit backfills xmax_ts).  Raises on write-write conflict with
        another in-progress deleter (the reference blocks on the first
        updater's xid; we use first-deleter-wins + error, serializable-lite).
        Returns a (chunk_idx, row_indexes) span for the txn's backfill list.
        """
        with self._mu:
            ch = self.chunks[chunk_idx]
            idx = np.nonzero(row_mask[:ch.nrows])[0]
            other = ch.xmax_txid[idx]
            conflict = (other != NO_TXID) & (other != txid)
            if conflict.any():
                raise WriteConflict(
                    f"row already deleted by in-progress txn "
                    f"{int(other[conflict][0])}",
                    holder=other[conflict][0])
            if ch.lock_txid is not None:
                lk = ch.lock_txid[idx]
                lconf = (lk != NO_TXID) & (lk != txid)
                if lconf.any():
                    raise WriteConflict(
                        f"row locked by in-progress txn "
                        f"{int(lk[lconf][0])}", holder=lk[lconf][0])
            ch.xmax_txid[idx] = txid
            self._note_mutation(self._idx_spans_min_row(
                [(chunk_idx, idx)]))
            return (chunk_idx, idx)

    def lock_rows(self, chunk_idx: int, row_mask: np.ndarray,
                  txid: int) -> tuple[int, np.ndarray]:
        """SELECT FOR UPDATE: stamp row locks without deleting
        (reference: heap_lock_tuple with LockTupleExclusive — xmax used
        as a lock marker, HEAP_XMAX_LOCK_ONLY).  Conflicts with other
        in-progress deleters AND other lockers; same wait protocol as
        mark_delete.  Returns a (chunk_idx, row_indexes) span cleared at
        txn end."""
        with self._mu:
            ch = self.chunks[chunk_idx]
            idx = np.nonzero(row_mask[:ch.nrows])[0]
            other = ch.xmax_txid[idx]
            conflict = (other != NO_TXID) & (other != txid)
            if conflict.any():
                raise WriteConflict(
                    f"row being deleted by in-progress txn "
                    f"{int(other[conflict][0])}",
                    holder=other[conflict][0])
            la = ch.lock_array()
            lk = la[idx]
            lconf = (lk != NO_TXID) & (lk != txid)
            if lconf.any():
                raise WriteConflict(
                    f"row locked by in-progress txn "
                    f"{int(lk[lconf][0])}", holder=lk[lconf][0])
            la[idx] = txid
            return (chunk_idx, idx)

    def truncate(self):
        """Drop every row immediately (reference: ExecuteTruncate —
        non-MVCC, the relfilenode swap).  Dictionaries survive (codes
        may be referenced by WAL records not yet checkpointed).  Takes
        the store mutex: concurrent host-op inserts must never append
        into a chunk list being replaced."""
        with self._mu:
            self.chunks = []
            self.ann_indexes = {}
            self.btree_indexes = {}
            self.null_columns = set()
            self._note_mutation(0)

    def clear_locks(self, spans):
        for ci, idx in spans:
            ch = self.chunks[ci]
            if ch.lock_txid is not None:
                ch.lock_txid[idx] = NO_TXID

    # -- commit/abort backfill (the CSN-log analog: we resolve commit
    #    timestamps into the hint columns eagerly, host-side; reference
    #    defers via csnlog.c + tqual.c hint-bit stamping).  All backfills
    #    are span-driven: commit cost is O(rows touched), not O(table). --
    def backfill_insert(self, spans, ts: np.int64):
        self._note_mutation(self._spans_min_row(spans))
        for ci, lo, hi in spans:
            self.chunks[ci].xmin_ts[lo:hi] = ts

    def abort_insert(self, spans):
        self._note_mutation(self._spans_min_row(spans))
        for ci, lo, hi in spans:
            self.chunks[ci].xmin_ts[lo:hi] = ABORTED_TS

    def backfill_delete(self, spans, ts: np.int64):
        self._note_mutation(self._idx_spans_min_row(spans))
        for ci, idx in spans:
            self.chunks[ci].xmax_ts[idx] = ts

    def revert_delete(self, spans):
        self._note_mutation(self._idx_spans_min_row(spans))
        for ci, idx in spans:
            self.chunks[ci].xmax_txid[idx] = NO_TXID

    # ------------------------------------------------------------------
    # ALTER TABLE column surgery (reference: tablecmds.c ATExecAddColumn
    # / ATExecDropColumn / renameatt — here columnar, so a column op is
    # a per-chunk array-dict edit, never a rewrite)
    def alter_add_column(self, cd) -> None:
        """Existing rows read NULL in the new column (typed zero fill +
        all-set null bitmap, the t_bits analog)."""
        if not self.td.has_column(cd.name):
            self.td.columns.append(cd)
        from ..catalog.types import TypeKind as _TK
        if cd.type.kind == _TK.TEXT and cd.name not in self.dicts:
            self.dicts[cd.name] = StringDict()
        filled = False
        for ch in self.chunks:
            if cd.name not in ch.columns:
                ch.columns[cd.name] = np.zeros(
                    (ch.cap, *cd.type.shape_suffix),
                    dtype=cd.type.np_dtype)
                ch.nulls[cd.name] = np.ones(ch.cap, dtype=bool)
                filled = True
        if filled:
            self.null_columns.add(cd.name)
        self._note_mutation(0)

    def alter_drop_column(self, name: str) -> None:
        self.td.columns = [c for c in self.td.columns if c.name != name]
        for ch in self.chunks:
            ch.columns.pop(name, None)
            ch.nulls.pop(name, None)
        self.dicts.pop(name, None)
        self.null_columns.discard(name)
        self._note_mutation(0)

    def alter_rename_column(self, old: str, new: str) -> None:
        for c in self.td.columns:
            if c.name == old:
                c.name = new
        for ch in self.chunks:
            if old in ch.columns:
                ch.columns[new] = ch.columns.pop(old)
            if old in ch.nulls:
                ch.nulls[new] = ch.nulls.pop(old)
        if old in self.dicts:
            self.dicts[new] = self.dicts.pop(old)
        if old in self.null_columns:
            self.null_columns.discard(old)
            self.null_columns.add(new)
        self._note_mutation(0)

    # ------------------------------------------------------------------
    def scan_chunks(self) -> Iterator[tuple[int, Chunk]]:
        for i, ch in enumerate(self.chunks):
            if ch.nrows:
                yield i, ch

    def vacuum(self, cutoff_ts: int) -> int:
        """Reclaim dead rows: drop versions deleted before cutoff_ts and
        aborted inserts; compact chunks (reference: lazy vacuum +
        shard-granular vacuum, pgxc/shard/shard_vacuum.c).  Returns rows
        reclaimed."""
        reclaimed = 0
        new_chunks: list[Chunk] = []
        for ch in self.chunks:
            n = ch.nrows
            if n == 0:
                continue
            dead = ((ch.xmax_ts[:n] <= cutoff_ts)
                    | (ch.xmin_ts[:n] == ABORTED_TS))
            keep = ~dead
            reclaimed += int(dead.sum())
            if keep.all():
                new_chunks.append(ch)
                continue
            idx = np.nonzero(keep)[0]
            kept = Chunk(
                columns={name: arr[:n][idx].copy()
                         for name, arr in ch.columns.items()},
                xmin_ts=ch.xmin_ts[:n][idx].copy(),
                xmax_ts=ch.xmax_ts[:n][idx].copy(),
                xmin_txid=ch.xmin_txid[:n][idx].copy(),
                xmax_txid=ch.xmax_txid[:n][idx].copy(),
                shardid=ch.shardid[:n][idx].copy(),
                nrows=len(idx), cap=len(idx) if len(idx) else 1,
                nulls={name: m[:n][idx].copy()
                       for name, m in ch.nulls.items()})
            if kept.nrows:
                new_chunks.append(kept)
        self.chunks = new_chunks
        self._note_mutation(0)
        return reclaimed

    def rows_of_shards(self, shard_ids: set) -> dict:
        """Extract live rows belonging to the given shard ids (for online
        shard movement, reference: pgxc/locator/redistrib.c).  NULL
        positions come back as python None in the value lists (the wire
        form re-splits them at the destination)."""
        sel_cols: dict[str, list] = {c.name: [] for c in self.td.columns}
        sids = []
        masks = []
        for ci, ch in self.scan_chunks():
            n = ch.nrows
            m = np.isin(ch.shardid[:n], list(shard_ids)) & \
                (ch.xmax_ts[:n] == INF_TS) & (ch.xmin_ts[:n] < INF_TS)
            masks.append((ci, m))
            if m.any():
                for name in sel_cols:
                    vals = ch.columns[name][:n][m]
                    ct = self.td.column(name).type
                    if ct.kind == TypeKind.TEXT:
                        out = self.dicts[name].decode(vals)
                    elif ct.kind == TypeKind.DECIMAL:
                        # exact decimal strings: the raw-insert path at
                        # the destination re-scales python ints, which
                        # would multiply stored (already-scaled) values
                        # by 10^scale again
                        out = [_decimal_str(int(v), ct.scale)
                               for v in vals.tolist()]
                    else:
                        out = vals.tolist()
                    nm = ch.nulls.get(name)
                    if nm is not None:
                        out = [None if isnull else v for v, isnull
                               in zip(out, nm[:n][m])]
                    sel_cols[name].extend(out)
                sids.extend(ch.shardid[:n][m].tolist())
        n_out = len(sids)
        return {"columns": sel_cols, "shardids":
                np.asarray(sids, dtype=np.int32), "n": n_out,
                "masks": masks}

    def build_ann_index(self, col: str, lists: int = 0,
                        metric: str = "l2", nprobe: int = 0) -> int:
        """IVFFlat coarse quantizer over a VECTOR column (kmeans over
        this store's rows) — contrib/pgvector ivfflat analog."""
        cd = self.td.column(col)
        if cd.type.kind != TypeKind.VECTOR:
            raise ValueError(
                f"ivfflat index requires a vector column, {col!r} is "
                f"{cd.type}")
        from ..ops.ann import kmeans
        parts = [ch.columns[col][:ch.nrows] for _, ch in
                 self.scan_chunks()]
        vecs = np.concatenate(parts) if parts else \
            np.zeros((0, cd.type.dim), np.float32)
        n = len(vecs)
        if lists <= 0:
            lists = max(1, min(int(np.sqrt(max(n, 1))), 1024))
        if nprobe <= 0:
            nprobe = max(1, lists // 8)
        centroids = kmeans(vecs.astype(np.float32), lists) if n else \
            np.zeros((lists, cd.type.dim), np.float32)
        self.ann_indexes[col] = {"centroids": centroids, "metric": metric,
                                 "nprobe": nprobe,
                                 "version": self.version}
        return lists

    def build_hnsw_index(self, col: str, m: int = 16,
                         ef_construction: int = 64,
                         metric: str = "l2") -> int:
        """HNSW graph over a VECTOR column (contrib/pgvector hnsw.c
        analog; ops/hnsw.py).  Rebuilt lazily when the store version
        moves (pgvector inserts incrementally; bulk rebuild first)."""
        cd = self.td.column(col)
        if cd.type.kind != TypeKind.VECTOR:
            raise ValueError(
                f"hnsw index requires a vector column, {col!r} is "
                f"{cd.type}")
        from ..ops import hnsw as H
        parts = [ch.columns[col][:ch.nrows] for _, ch in
                 self.scan_chunks()]
        vecs = np.concatenate(parts) if parts else \
            np.zeros((0, cd.type.dim), np.float32)
        self.ann_indexes[col] = {
            "kind": "hnsw", "metric": metric, "m": m,
            "ef_construction": ef_construction,
            "index": H.build(vecs.astype(np.float32), metric, m,
                             ef_construction),
            "version": self.version,
        }
        return len(vecs)

    def hnsw_index(self, col: str):
        """Current HNSW index for a column (rebuilding on staleness),
        or None."""
        info = self.ann_indexes.get(col)
        if info is None or info.get("kind") != "hnsw":
            return None
        if info.get("version") != self.version:
            self.build_hnsw_index(col, info["m"],
                                  info["ef_construction"],
                                  info["metric"])
            info = self.ann_indexes[col]
        return info

    def build_btree_index(self, col: str) -> int:
        """(Re)build the sorted index over one column.  Positions address
        the live-row concatenation order scans use.  Rebuilds are lazy:
        lookups rebuild when the store version moved (write-heavy
        workloads amortize; incremental maintenance is a follow-up —
        reference nbtree inserts keys per tuple)."""
        cd = self.td.column(col)
        if cd.type.kind == TypeKind.VECTOR:
            raise ValueError("btree index unsupported on vector columns")
        parts = [ch.columns[col][:ch.nrows] for _, ch in
                 self.scan_chunks()]
        arr = np.concatenate(parts) if parts else \
            np.empty(0, cd.type.np_dtype)
        order = np.argsort(arr, kind="stable")
        self.btree_indexes[col] = {
            "keys": np.ascontiguousarray(arr[order]),
            "pos": order.astype(np.int64),
            "version": self.version,
        }
        return len(arr)

    def btree_lookup(self, col: str, lo=None, hi=None,
                     lo_strict: bool = False,
                     hi_strict: bool = False) -> Optional[np.ndarray]:
        """Live-row positions whose `col` value is within [lo, hi]
        (bounds optional, strictness per side); None when no index."""
        idx = self.btree_indexes.get(col)
        if idx is None:
            return None
        if idx["version"] != self.version:
            self.build_btree_index(col)
            idx = self.btree_indexes[col]
        keys = idx["keys"]
        a = 0 if lo is None else int(np.searchsorted(
            keys, lo, side="right" if lo_strict else "left"))
        b = len(keys) if hi is None else int(np.searchsorted(
            keys, hi, side="left" if hi_strict else "right"))
        return np.sort(idx["pos"][a:b])

    def host_live_columns(self, colnames,
                          start: int = 0) -> dict[str, np.ndarray]:
        """Live-row concatenation (scan order) of the given value
        columns plus MVCC sys columns and null masks — the ONE host
        source the staging tiers (spill slabs/partitions, mesh sharding,
        index-scan subsets) slice from.  With `start`, only rows at scan
        positions >= start are returned — the buffer pool's incremental
        tail-staging path (appended_only_since proves the prefix is
        already resident, so only the tail ever touches the host)."""
        want = set(colnames)
        nullcols = {c for c in want if c in self.null_columns}
        host: dict[str, np.ndarray] = {}
        chunks: list[tuple[Chunk, int]] = []   # (chunk, row offset)
        cum = 0
        for _, ch in self.scan_chunks():
            lo = max(0, start - cum)
            cum += ch.nrows
            if lo < ch.nrows:
                chunks.append((ch, lo))
        for name in want:
            cd = self.td.column(name)
            arrs = [ch.columns[name][lo:ch.nrows] for ch, lo in chunks]
            host[name] = np.concatenate(arrs) if arrs else \
                np.empty((0, *cd.type.shape_suffix), cd.type.np_dtype)
        for sys in ("xmin_ts", "xmax_ts", "xmin_txid", "xmax_txid"):
            arrs = [getattr(ch, sys)[lo:ch.nrows] for ch, lo in chunks]
            host[f"__{sys}"] = np.concatenate(arrs) if arrs else \
                np.empty(0, np.int64)
        for name in nullcols:
            arrs = [ch.nulls[name][lo:ch.nrows] if name in ch.nulls
                    else np.zeros(ch.nrows - lo, bool)
                    for ch, lo in chunks]
            host[f"__null.{name}"] = np.concatenate(arrs) if arrs else \
                np.zeros(0, bool)
        return host

    def gather_rows(self, positions: np.ndarray,
                    colnames) -> dict[str, np.ndarray]:
        """Host gather of specific live rows (positions in scan
        concatenation order) — O(k + chunks), the index-scan staging
        path.  Returns value columns + MVCC sys columns + null masks."""
        chunks = [ch for _, ch in self.scan_chunks()]
        starts = np.cumsum([0] + [ch.nrows for ch in chunks])
        ci = np.searchsorted(starts, positions, side="right") - 1
        off = positions - starts[ci]
        out: dict[str, np.ndarray] = {}
        names = list(colnames)
        nullcols = [c for c in names if c in self.null_columns]
        k = len(positions)
        for name in names:
            cd = self.td.column(name)
            buf = np.empty((k, *cd.type.shape_suffix), cd.type.np_dtype)
            for i, ch in enumerate(chunks):
                m = ci == i
                if m.any():
                    buf[m] = ch.columns[name][off[m]]
            out[name] = buf
        for sys in ("xmin_ts", "xmax_ts", "xmin_txid", "xmax_txid"):
            buf = np.empty(k, np.int64)
            for i, ch in enumerate(chunks):
                m = ci == i
                if m.any():
                    buf[m] = getattr(ch, sys)[off[m]]
            out[f"__{sys}"] = buf
        for name in nullcols:
            buf = np.zeros(k, bool)
            for i, ch in enumerate(chunks):
                m = ci == i
                if m.any() and name in ch.nulls:
                    buf[m] = ch.nulls[name][off[m]]
            out[f"__null.{name}"] = buf
        return out

    def visible_mask(self, ch: Chunk, snap_ts: int, my_txid: int) -> np.ndarray:
        """Host-side reference implementation of the visibility rule; the
        device kernel in ops/visibility.py computes the same mask fused into
        scans (reference: HeapTupleSatisfiesMVCC, tqual.c:1203,2133)."""
        n = ch.nrows
        xmin_ts = ch.xmin_ts[:n]
        xmax_ts = ch.xmax_ts[:n]
        ins_visible = (xmin_ts <= snap_ts) | (
            (ch.xmin_txid[:n] == my_txid) & (xmin_ts != ABORTED_TS))
        del_visible = (xmax_ts <= snap_ts) | (ch.xmax_txid[:n] == my_txid)
        return ins_visible & ~del_visible
