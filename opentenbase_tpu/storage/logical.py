"""Logical replication: shard-aware row-level pub/sub.

Reference analog: logical decoding + the subscription apply worker
(src/backend/replication/logical/worker.c:3369 — OpenTenBase's apply is
"shard-aware": rows route through the SUBSCRIBER's own shard map, not
the publisher's) and contrib/opentenbase_subscription (multi-active
subscription with origin filtering).

Pipeline:
- Every DataNode gets a `LogicalDecoder` hook fed from the same write
  paths that produce WAL (insert_raw / delete_where / commit / abort).
  Changes buffer per txid and publish atomically at commit with the
  commit GTS — the decoding the reference does from WAL happens here
  at the logging boundary, where values and dictionaries are in hand.
- A `LogicalPublisher` owns publications (name -> table set) and
  replication slots; each committed txn's changes fan out to every
  slot whose publication covers the table.
- A `Subscription` (subscriber side) drains a slot — in-process or
  over TCP (`LogicalPubServer`) — and applies each txn atomically
  through the subscriber's OWN distribution: inserts route via its
  locator (shard-aware apply), deletes match by replica identity and
  fan to its datanodes.  One publisher txn = one subscriber txn
  (implicit 2PC when rows span datanodes).
- Multi-active: txns created by replication apply are tagged in
  `cluster.replication_origin_txids`; the decoder drops them at commit,
  so A<->B subscriptions do not loop (reference: replication origins,
  opentenbase_subscription's multi-active mode).

A publisher txn that wrote on N datanodes decodes as N changesets
(same txid, one per participant) — each applies as its own subscriber
txn, so cross-datanode publisher atomicity relaxes to row-level
eventual convergence, exactly like the reference's per-node walsender
streams.

Replica identity is FULL ROW (the engine's tables carry no catalog'd
PK): a delete ships every column of the deleted rows; with exact
duplicate rows the apply may delete a different-but-identical copy,
which is observationally equivalent.

Initial sync: the slot attaches FIRST, then the snapshot is cut at GTS
S; the apply skips streamed txns with commit ts <= S, so nothing is
double-applied (reference: the tablesync worker's catchup protocol).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..catalog.types import TypeKind
from ..obs import xray
from ..utils import locks


# in-process connection registry: CREATE SUBSCRIPTION ... CONNECTION
# 'local:<key>' resolves here (tests and single-host deployments);
# 'tcp:host:port' goes over the wire
_publishers_lock = locks.Lock("storage.logical._publishers_lock")
_LOCAL_PUBLISHERS: dict[str, "LogicalPublisher"] = {}  # guarded_by: _publishers_lock


def register_local_publisher(key: str, pub: "LogicalPublisher"):
    with _publishers_lock:
        _LOCAL_PUBLISHERS[key] = pub


def _dec_str(v: int, scale: int) -> str:
    """Exact storage-int -> decimal-string round-trip (decimal_to_int
    parses it back to the identical int)."""
    if scale == 0:
        return str(int(v))
    sign = "-" if v < 0 else ""
    a = abs(int(v))
    return f"{sign}{a // 10 ** scale}.{a % 10 ** scale:0{scale}d}"


def _decode_column(col_def, arr: np.ndarray, nulls: Optional[np.ndarray],
                   dicts) -> list:
    """Storage representation -> python-raw values (None for NULL) that
    DataNode.insert_raw re-encodes exactly."""
    k = col_def.type.kind
    if k == TypeKind.TEXT:
        d = dicts[col_def.name].values if col_def.name in dicts else []
        table = np.asarray(list(d) + [""], dtype=object)
        vals = table[np.clip(arr, 0, len(table) - 1)].tolist()
    elif k == TypeKind.DECIMAL:
        s = col_def.type.scale
        vals = [_dec_str(v, s) for v in arr.tolist()]
    elif k == TypeKind.FLOAT64:
        vals = [float(v) for v in arr.tolist()]
    elif k == TypeKind.VECTOR:
        vals = [[float(x) for x in v] for v in arr.tolist()]
    else:
        vals = [int(v) for v in arr.tolist()]
    if nulls is not None:
        vals = [None if m else v for v, m in zip(vals, nulls)]
    return vals


class LogicalDecoder:
    """Per-datanode change capture; emits committed txn changesets."""

    def __init__(self, dn, sink, should_capture=None):
        self.dn = dn
        self.sink = sink                      # fn(txn_dict)
        # predicate(table) -> bool: decode only tables some live slot
        # subscribes to (a bulk load into an unpublished table must not
        # pay per-value decode cost)
        self.should_capture = should_capture or (lambda table: True)
        self.pending: dict[int, list] = {}
        self._lock = locks.Lock("storage.logical.LogicalDecoder._lock")

    def on_insert(self, table: str, store, enc: dict, masks: dict,
                  n: int, txid: int):
        if not self.should_capture(table):
            return
        cols = {}
        for cname, arr in enc.items():
            cd = store.td.column(cname)
            nulls = masks.get(cname)
            cols[cname] = _decode_column(cd, np.asarray(arr), nulls,
                                         store.dicts)
        with self._lock:
            self.pending.setdefault(txid, []).append(
                {"kind": "insert", "table": table, "cols": cols,
                 "n": n})

    def on_delete(self, table: str, store, ch, mask: np.ndarray,
                  txid: int):
        if not self.should_capture(table):
            return
        idx = np.nonzero(mask[:ch.nrows])[0]
        if len(idx) == 0:
            return
        rows = {}
        for cd in store.td.columns:
            arr = ch.columns[cd.name][:ch.nrows][idx]
            nm = ch.nulls.get(cd.name)
            nulls = nm[:ch.nrows][idx] if nm is not None else None
            rows[cd.name] = _decode_column(cd, arr, nulls, store.dicts)
        with self._lock:
            self.pending.setdefault(txid, []).append(
                {"kind": "delete", "table": table, "rows": rows,
                 "n": len(idx)})

    def on_commit(self, txid: int, ts: int):
        with self._lock:
            changes = self.pending.pop(txid, None)
        if not changes:
            return
        self.sink({"txid": txid, "ts": int(ts), "dn": self.dn.index,
                   "changes": changes})

    def on_abort(self, txid: int):
        with self._lock:
            self.pending.pop(txid, None)


class ReplicationSlot:
    """Retained change stream for one subscription (reference:
    replication slots — changes are kept until consumed)."""

    def __init__(self, slot_id: int, tables: frozenset):
        self.slot_id = slot_id
        self.tables = tables
        self._q: list = []
        self._cv = locks.Condition(name="storage.logical.ReplicationSlot._cv")
        self.closed = False

    def push(self, txn: dict):
        changes = [c for c in txn["changes"] if c["table"] in self.tables]
        if not changes:
            return
        with self._cv:
            self._q.append({**txn, "changes": changes})
            self._cv.notify_all()

    def poll(self, max_txns: int = 64, timeout: float = 0.2) -> list:
        with self._cv:
            if not self._q:
                with xray.wait_event("logical-poll"):
                    self._cv.wait(timeout)
            out, self._q = self._q[:max_txns], self._q[max_txns:]
            return out


class LogicalPublisher:
    """Publisher-side registry: publications + slots + decoder wiring."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.pubs: dict[str, list[str]] = {}
        self.slots: dict[int, ReplicationSlot] = {}
        self._next_slot = 1
        self._lock = locks.Lock("storage.logical.LogicalPublisher._lock")
        for dn in cluster.datanodes:
            if getattr(dn, "decoder", None) is None and \
                    hasattr(dn, "stores"):
                dn.decoder = LogicalDecoder(dn, self._on_txn,
                                            self._slot_covers)

    def _slot_covers(self, table: str) -> bool:
        with self._lock:
            return any(table in s.tables for s in self.slots.values())

    def _on_txn(self, txn: dict):
        if txn["txid"] in self.cluster.replication_origin_txids:
            return          # replication-applied: do not re-publish
        with self._lock:
            slots = list(self.slots.values())
        for s in slots:
            s.push(txn)

    def create_publication(self, name: str, tables: list[str]):
        for t in tables:
            self.cluster.catalog.table(t)     # must exist
        self.pubs[name] = list(tables)

    def drop_publication(self, name: str):
        self.pubs.pop(name, None)

    def create_slot(self, publication: str):
        """Attach a slot, then cut the snapshot — streamed txns with
        ts <= snapshot_ts are skipped by the apply.

        Consistent point (reference: the tablesync worker's catchup
        protocol / SnapBuild), two drain rounds:
        1. txns in flight at slot ATTACH may have written before the
           decoder captured for this slot (partial streams) — they must
           commit BEFORE snapshot_ts is drawn, so the snapshot carries
           them whole and the ts filter drops their partial changesets;
        2. txns starting after the attach are fully captured, but any
           that commit with ts <= snapshot_ts must have their backfill
           land before the snapshot scan reads visibility."""
        tables = self.pubs.get(publication)
        if tables is None:
            raise KeyError(f"publication {publication!r} does not exist")
        with self._lock:
            sid = self._next_slot
            self._next_slot += 1
            slot = ReplicationSlot(sid, frozenset(tables))
            self.slots[sid] = slot

        def drain(txids: set, what: str):
            deadline = time.time() + 30.0
            while txids & self.cluster.active_txns:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"initial sync: {what} transactions did not "
                        "drain within 30s")
                time.sleep(0.01)

        drain(set(self.cluster.active_txns), "pre-attach")
        snapshot_ts = int(self.cluster.gtm.next_gts())
        drain(set(self.cluster.active_txns), "concurrent")
        txid = int(self.cluster.gtm.next_txid())
        snapshot = {}
        for t in tables:
            snapshot[t] = self._snapshot_table(t, snapshot_ts, txid)
        return sid, snapshot_ts, snapshot

    def _snapshot_table(self, table: str, ts: int, txid: int) -> dict:
        td = self.cluster.catalog.table(table)
        cols: dict[str, list] = {c.name: [] for c in td.columns}
        n = 0
        from ..catalog.schema import DistType
        dns = self.cluster.datanodes
        if td.distribution.dist_type == DistType.REPLICATED:
            dns = dns[:1]                     # read-one
        for dn in dns:
            store = dn.stores[table]
            for _, ch in store.scan_chunks():
                vis = store.visible_mask(ch, ts, txid)
                idx = np.nonzero(vis[:ch.nrows])[0]
                if len(idx) == 0:
                    continue
                for cd in td.columns:
                    arr = ch.columns[cd.name][:ch.nrows][idx]
                    nm = ch.nulls.get(cd.name)
                    nulls = nm[:ch.nrows][idx] if nm is not None else None
                    cols[cd.name].extend(
                        _decode_column(cd, arr, nulls, store.dicts))
                n += len(idx)
        return {"cols": cols, "n": n}

    def drop_slot(self, sid: int):
        with self._lock:
            s = self.slots.pop(sid, None)
        if s is not None:
            s.closed = True


class Subscription:
    """Subscriber-side apply worker (reference: the logical replication
    apply worker, worker.c)."""

    def __init__(self, name: str, sub_cluster, conninfo: str,
                 publication: str):
        self.name = name
        self.cluster = sub_cluster
        self.publication = publication
        self.applied_txns = 0
        self.last_applied_ts = 0
        self.last_error = ""
        self._stop = threading.Event()
        self._client = self._connect(conninfo)
        sid, snap_ts, snapshot = self._client.create_slot(publication)
        self._sid = sid
        self._snapshot_ts = snap_ts
        self._apply_snapshot(snapshot)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- connection --------------------------------------------------------
    def _connect(self, conninfo: str):
        if conninfo.startswith("local:"):
            pub = _LOCAL_PUBLISHERS[conninfo[6:]]
            return _InProcClient(pub)
        if conninfo.startswith("tcp:"):
            host, port = conninfo[4:].rsplit(":", 1)
            return LogicalPubClient(host, int(port))
        raise ValueError(f"bad conninfo {conninfo!r} "
                         "(local:<key> or tcp:host:port)")

    # -- apply -------------------------------------------------------------
    def _apply_snapshot(self, snapshot: dict):
        for table, payload in snapshot.items():
            if payload["n"]:
                self._apply_insert(table, payload["cols"], payload["n"],
                                   txn=None)

    def _run(self):
        while not self._stop.is_set():
            try:
                txns = self._client.poll(self._sid)
            except (ConnectionError, OSError, EOFError):
                time.sleep(0.5)
                continue
            for txn in txns:
                if txn["ts"] <= self._snapshot_ts:
                    continue                  # covered by the snapshot
                # apply errors retry with backoff instead of silently
                # killing the worker (reference: the apply worker exits
                # and the launcher restarts it, retrying the same txn
                # until it succeeds or the subscription is dropped)
                while not self._stop.is_set():
                    try:
                        self._apply_txn(txn)
                        self.last_error = ""
                        break
                    except Exception as e:       # noqa: BLE001
                        self.last_error = f"{type(e).__name__}: {e}"
                        # retry backoff tick, not a query stall
                        self._stop.wait(1.0)  # otblint: disable=wait-discipline

    def _apply_txn(self, txn: dict):
        c = self.cluster
        txid = int(c.gtm.next_txid())
        snapshot_ts = int(c.gtm.next_gts())
        c.replication_origin_txids.add(txid)
        written: set[int] = set()
        try:
            for ch in txn["changes"]:
                if ch["kind"] == "insert":
                    written |= self._apply_insert(
                        ch["table"], ch["cols"], ch["n"],
                        txn=(txid, snapshot_ts))
                else:
                    written |= self._apply_delete(
                        ch["table"], ch["rows"], ch["n"], txid,
                        snapshot_ts)
            c.commit_txn(txid, sorted(written))
            self.applied_txns += 1
            self.last_applied_ts = txn["ts"]
        except Exception:
            c.abort_txn(txid, written)
            raise

    def _apply_insert(self, table: str, cols: dict, n: int,
                      txn) -> set:
        """Shard-aware apply: rows route through the SUBSCRIBER's
        locator/shard map (worker.c:3369's shard-aware insert)."""
        c = self.cluster
        td = c.catalog.table(table)
        from ..catalog.schema import DistType
        if txn is None:
            txid = int(c.gtm.next_txid())
            c.replication_origin_txids.add(txid)
        else:
            txid, _ = txn
        written: set[int] = set()
        if td.distribution.dist_type == DistType.REPLICATED:
            dests = {dn.index: np.arange(n) for dn in c.datanodes}
            sid = None
        else:
            route_cols = {}
            for cn in td.distribution.dist_cols:
                vals = cols[cn]
                fill = "" if td.column(cn).type.kind == TypeKind.TEXT \
                    else 0
                route_cols[cn] = np.asarray(
                    [fill if v is None else v for v in vals])
            nodes = c.locator.route_rows(td, route_cols, n)
            sid = c.locator.shard_ids_for_rows(td, route_cols)
            dests = {i: np.nonzero(nodes == i)[0]
                     for i in set(nodes.tolist())}
        for dn_idx, idx in dests.items():
            if len(idx) == 0:
                continue
            sub = {cn: [cols[cn][j] for j in idx] for cn in cols}
            sub_sid = sid[idx] if sid is not None else None
            c.datanodes[dn_idx].insert_raw(table, sub, len(idx), txid,
                                           sub_sid)
            written.add(dn_idx)
        if txn is None:
            c.commit_txn(txid, sorted(written))
        return written

    def _apply_delete(self, table: str, rows: dict, n: int, txid: int,
                      snapshot_ts: int) -> set:
        """Replica-identity-full delete: per row, a conjunction over
        every column; rows OR together (chunked)."""
        from ..plan import exprs as E
        from ..catalog import types as T
        c = self.cluster
        td = c.catalog.table(table)
        written: set[int] = set()
        names = list(rows)
        row_quals = []
        for i in range(n):
            conj = []
            for cn in names:
                cd = td.column(cn)
                qname = f"{table}.{cn}"
                v = rows[cn][i]
                if v is None:
                    conj.append(E.IsNull(E.Col(qname, cd.type)))
                elif cd.type.kind == TypeKind.TEXT:
                    conj.append(E.StrPred(E.Col(qname, cd.type), "eq",
                                          (v,)))
                elif cd.type.kind == TypeKind.DECIMAL:
                    conj.append(E.Cmp(
                        "=", E.Col(qname, cd.type),
                        E.Lit(T.decimal_to_int(v, cd.type.scale),
                              cd.type)))
                else:
                    conj.append(E.Cmp("=", E.Col(qname, cd.type),
                                      E.Lit(v, cd.type)))
            row_quals.append(conj[0] if len(conj) == 1
                             else E.BoolOp("and", tuple(conj)))
        for lo in range(0, len(row_quals), 128):
            block = row_quals[lo:lo + 128]
            qual = block[0] if len(block) == 1 \
                else E.BoolOp("or", tuple(block))
            for dn in c.datanodes:
                if dn.delete_where(table, [qual], snapshot_ts, txid):
                    written.add(dn.index)
        return written

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        try:
            self._client.drop_slot(self._sid)
        except Exception:
            pass


class _InProcClient:
    def __init__(self, pub: LogicalPublisher):
        self.pub = pub

    def create_slot(self, publication):
        return self.pub.create_slot(publication)

    def poll(self, sid):
        slot = self.pub.slots.get(sid)
        if slot is None:
            raise ConnectionError("slot dropped")
        return slot.poll()

    def drop_slot(self, sid):
        self.pub.drop_slot(sid)


class LogicalPubServer:
    """TCP front end for a LogicalPublisher (the walsender analog for
    logical subscriptions)."""

    def __init__(self, publisher: LogicalPublisher,
                 host: str = "127.0.0.1", port: int = 0):
        import socketserver
        from ..net.wire import recv_msg, send_msg
        pub = publisher

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    if msg is None:
                        return
                    try:
                        op = msg.get("op")
                        if op == "create_slot":
                            sid, ts, snap = pub.create_slot(
                                msg["publication"])
                            resp = {"ok": True, "sid": sid, "ts": ts,
                                    "snapshot": snap}
                        elif op == "poll":
                            slot = pub.slots.get(msg["sid"])
                            if slot is None:
                                resp = {"error": "slot dropped"}
                            else:
                                resp = {"ok": True,
                                        "txns": slot.poll()}
                        elif op == "drop_slot":
                            pub.drop_slot(msg["sid"])
                            resp = {"ok": True}
                        else:
                            resp = {"error": f"unknown op {op!r}"}
                    except Exception as e:
                        resp = {"error": str(e)}
                    send_msg(self.request, resp)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class LogicalPubClient:
    def __init__(self, host: str, port: int):
        import socket
        from ..net.wire import recv_msg, send_msg
        self._send, self._recv = send_msg, recv_msg
        self._sock = socket.create_connection((host, port), timeout=30)
        self._lock = locks.Lock("storage.logical.LogicalPubClient._lock")

    def _call(self, msg: dict) -> dict:
        with self._lock:
            self._send(self._sock, msg)
            resp = self._recv(self._sock)
        if resp is None or resp.get("error"):
            raise ConnectionError(str(resp))
        return resp

    def create_slot(self, publication):
        r = self._call({"op": "create_slot", "publication": publication})
        return r["sid"], r["ts"], r["snapshot"]

    def poll(self, sid):
        return self._call({"op": "poll", "sid": sid})["txns"]

    def drop_slot(self, sid):
        self._call({"op": "drop_slot", "sid": sid})
