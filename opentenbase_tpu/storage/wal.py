"""Write-ahead log + checkpoints for a datanode.

Reference analog: src/backend/access/transam/xlog.c (13.6k LoC of WAL) +
postmaster/checkpointer.c.  Scope here is the columnar engine's needs:
redo-only logical records (insert batches, delete marks, commit/abort with
GTS, DDL), a length+crc framed binary file, and full-snapshot checkpoints
(npz per table) that truncate the log.  Recovery = load checkpoint, replay
tail, resolve in-doubt prepared txns via the 2PC resolver (txn/twophase.py).
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import zlib
from typing import Iterator, Optional

import numpy as np

_HDR = struct.Struct("<II")  # length, crc32


class Wal:
    def __init__(self, path: str, ship=None, sync_ship: bool = True):
        """``ship``: optional hook receiving every framed record as raw
        bytes (streaming replication to a DnStandby,
        storage/replication.py).  Sync mode propagates ship failures so
        the statement is never ACKNOWLEDGED unless the standby durably
        took the record.  As in the reference (synchronous_commit waits
        AFTER the local WAL flush, syncrep.c), the record is already
        locally durable at that point: a crash may recover an
        UNACKNOWLEDGED transaction as committed — acknowledged ones are
        always on both sides.  Async mode keeps serving and flags
        ``standby_ok``."""
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        self._ship = ship
        self._sync_ship = sync_ship
        self.standby_ok = ship is not None

    def append(self, record: dict, sync: bool = False):
        blob = pickle.dumps(record, protocol=4)
        frame = _HDR.pack(len(blob), zlib.crc32(blob)) + blob
        self._f.write(frame)
        if sync:
            self.flush(fsync=True)
        if self._ship is not None:
            try:
                self._ship(frame)
                self.standby_ok = True
            except Exception:
                self.standby_ok = False
                if self._sync_ship:
                    raise

    def flush(self, fsync: bool = False):
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    def close(self):
        self._f.close()

    def truncate(self):
        """Post-checkpoint log reset."""
        self._f.close()
        self._f = open(self.path, "wb")

    @staticmethod
    def replay(path: str) -> Iterator[dict]:
        """Yield records up to the first torn/corrupt tail (crash-safe)."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HDR.size <= len(data):
            length, crc = _HDR.unpack_from(data, off)
            off += _HDR.size
            if off + length > len(data):
                return  # torn tail
            blob = data[off:off + length]
            if zlib.crc32(blob) != crc:
                return  # corrupt tail
            off += length
            yield pickle.loads(blob)


def decode_frame(frame: bytes) -> Optional[dict]:
    """One shipped frame (length+crc+blob) back to its record, or None
    when torn/corrupt — the hot standby's incremental-apply decoder
    (storage/replication.py HotStandby) shares the replay framing."""
    if len(frame) < _HDR.size:
        return None
    length, crc = _HDR.unpack_from(frame)
    blob = frame[_HDR.size:_HDR.size + length]
    if len(blob) != length or zlib.crc32(blob) != crc:
        return None
    return pickle.loads(blob)


def checkpoint_store(store, path: str):
    """Write one TableStore as an npz + dictionary sidecar.

    Also seals the LIVE store's layout to match what restore_store will
    rebuild: restored chunks come back exact-sized (cap == nrows), so
    post-checkpoint inserts open a fresh chunk there.  If the live store
    kept free capacity in its last chunk, post-checkpoint inserts would
    land at different (chunk, offset) coordinates live vs. replayed, and
    WAL delete records (addressed by chunk+offset) would hit the wrong
    rows after recovery.  Freezing cap at nrows (and dropping empty
    chunks, which checkpoints skip) makes both layouts agree.
    """
    sealed = [ch for ch in store.chunks if ch.nrows]
    arrays = {}
    for i, ch in enumerate(sealed):
        n = ch.nrows
        for name, arr in ch.columns.items():
            arrays[f"c{i}.{name}"] = arr[:n]
        arrays[f"c{i}.__xmin_ts"] = ch.xmin_ts[:n]
        arrays[f"c{i}.__xmax_ts"] = ch.xmax_ts[:n]
        arrays[f"c{i}.__xmin_txid"] = ch.xmin_txid[:n]
        arrays[f"c{i}.__xmax_txid"] = ch.xmax_txid[:n]
        arrays[f"c{i}.__shardid"] = ch.shardid[:n]
        for name, m in ch.nulls.items():
            if m[:n].any():
                arrays[f"c{i}.__null.{name}"] = m[:n]
    dicts = {name: d.values for name, d in store.dicts.items()}
    tmp = path + ".tmp"
    dict_blob = pickle.dumps(dicts, protocol=4)
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.write(dict_blob)
        # length-prefixed footer (no in-band sentinel: user strings may
        # contain anything)
        f.write(struct.pack("<Q", len(dict_blob)))
    os.replace(tmp, path)
    # Seal only after the checkpoint is durably in place: sealing first
    # would diverge the live layout from the (old) on-disk one if the
    # write failed mid-way.
    for ch in sealed:
        ch.cap = ch.nrows
    store.chunks = sealed


def restore_store(store, path: str):
    """Load a checkpoint back into an (empty) TableStore."""
    with open(path, "rb") as f:
        blob = f.read()
    (dict_len,) = struct.unpack("<Q", blob[-8:])
    split = len(blob) - 8 - dict_len
    npz = np.load(io.BytesIO(blob[:split]), allow_pickle=False)
    dicts = pickle.loads(blob[split:split + dict_len])
    from .store import Chunk, StringDict
    chunk_ids = sorted({int(k.split(".")[0][1:]) for k in npz.files})
    for ci in chunk_ids:
        names = [c.name for c in store.td.columns]
        cols = {n: np.array(npz[f"c{ci}.{n}"]) for n in names}
        nrows = len(next(iter(cols.values())))
        nulls = {}
        for n in names:
            key = f"c{ci}.__null.{n}"
            if key in npz.files:
                nulls[n] = np.array(npz[key])
                store.null_columns.add(n)
        ch = Chunk(
            columns={n: _grow(cols[n]) for n in names},
            xmin_ts=_grow(np.array(npz[f"c{ci}.__xmin_ts"])),
            xmax_ts=_grow(np.array(npz[f"c{ci}.__xmax_ts"])),
            xmin_txid=_grow(np.array(npz[f"c{ci}.__xmin_txid"])),
            xmax_txid=_grow(np.array(npz[f"c{ci}.__xmax_txid"])),
            shardid=_grow(np.array(npz[f"c{ci}.__shardid"])),
            nrows=nrows, cap=max(nrows, 1), nulls=nulls)
        ch.cap = len(next(iter(ch.columns.values())))
        store.chunks.append(ch)
    for name, values in dicts.items():
        d = StringDict()
        for v in values:
            d.encode_one(v)
        store.dicts[name] = d
    # goes through the mutation log (min_row=0): a restore rebuilds the
    # whole chunk list, so no cached device prefix may survive it
    store._note_mutation(0)


def _grow(arr: np.ndarray) -> np.ndarray:
    """Checkpointed chunks come back exactly-sized; keep them as-is (full
    chunks) — new inserts open fresh chunks."""
    return arr
