"""ColumnBatch — the unit of data flowing through the executor.

The reference executor moves one `TupleTableSlot` at a time through
`ExecProcNode` (src/backend/executor/execProcnode.c); its vestigial columnar
hooks (`TupleTableSlot.vector_ptr`, include/executor/tuptable.h:151-156) show
the direction this rebuild takes natively: operators exchange *columnar
batches* — a dict of equal-length arrays plus a row-count — because a batch
of columns is the shape a TPU kernel wants.

A batch's arrays may be numpy (host) or jax (device).  `sel` is an optional
boolean row mask (the fused qual/visibility output); kernels treat masked-out
rows as padding.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..catalog.types import SqlType


@dataclasses.dataclass
class ColumnSchema:
    name: str
    type: SqlType


@dataclasses.dataclass
class ColumnBatch:
    schema: list[ColumnSchema]
    columns: dict[str, object]          # name -> np.ndarray | jax.Array
    nrows: int
    sel: Optional[object] = None        # bool mask, len == nrows
    dicts: dict[str, list] = dataclasses.field(default_factory=dict)
    # dictionary for TEXT columns: name -> list[str], code -> string

    def col(self, name: str):
        return self.columns[name]

    def col_type(self, name: str) -> SqlType:
        for cs in self.schema:
            if cs.name == name:
                return cs.type
        raise KeyError(name)

    @property
    def names(self) -> list[str]:
        return [c.name for c in self.schema]

    def selected_count(self) -> int:
        if self.sel is None:
            return self.nrows
        return int(np.asarray(self.sel).sum())

    def materialize_host(self) -> "ColumnBatch":
        """Bring all columns to host numpy and apply `sel` compaction."""
        cols = {k: np.asarray(v) for k, v in self.columns.items()}
        n = self.nrows
        if self.sel is not None:
            mask = np.asarray(self.sel)[:n]
            cols = {k: v[:n][mask] for k, v in cols.items()}
            n = int(mask.sum())
        else:
            cols = {k: v[:n] for k, v in cols.items()}
        return ColumnBatch(self.schema, cols, n, None, dict(self.dicts))

    @staticmethod
    def concat(batches: list["ColumnBatch"]) -> "ColumnBatch":
        """Concatenate host batches (dictionaries must already be shared)."""
        batches = [b.materialize_host() for b in batches]
        if not batches:
            raise ValueError("concat of zero batches")
        first = batches[0]
        cols = {
            name: np.concatenate([b.columns[name] for b in batches])
            for name in first.columns
        }
        n = sum(b.nrows for b in batches)
        return ColumnBatch(first.schema, cols, n, None, dict(first.dicts))

    def to_pylist(self) -> list[tuple]:
        """Decode to python tuples (tests / client output)."""
        from ..catalog.types import TypeKind, days_to_date, int_to_decimal

        b = self.materialize_host()
        out_cols = []
        for cs in b.schema:
            arr = b.columns[cs.name]
            if cs.type.kind == TypeKind.TEXT:
                d = b.dicts.get(cs.name, [])
                out_cols.append([d[int(i)] if 0 <= int(i) < len(d) else None
                                 for i in arr])
            elif cs.type.kind == TypeKind.DECIMAL:
                out_cols.append([int_to_decimal(int(v), cs.type.scale)
                                 for v in arr])
            elif cs.type.kind == TypeKind.DATE:
                out_cols.append([days_to_date(int(v)) for v in arr])
            elif cs.type.kind == TypeKind.FLOAT64:
                out_cols.append([float(v) for v in arr])
            else:
                out_cols.append([int(v) for v in arr])
        return list(zip(*out_cols)) if out_cols else []


def next_pow2(n: int, floor: int = 256) -> int:
    """Size-class for padded device batches: keeps XLA recompiles bounded
    (the dynamic-shape strategy from SURVEY.md §7.3)."""
    p = floor
    while p < n:
        p <<= 1
    return p


def size_class(n: int, floor: int = 256) -> int:
    """Quarter-step size class {1, 1.25, 1.5, 1.75}*2^k: staged base
    tables live at one size for their whole lifetime, so the finer
    ladder trades 4x the (cached) compile classes for <=25% padding
    waste instead of <=100% — at SF1, lineitem pads to 6.29M instead
    of 8.39M, and every scan kernel's work drops with it."""
    p = floor
    while p < n:
        p <<= 1
    if p == floor:
        return p
    for num in (4, 5, 6, 7):
        c = (p >> 3) * num
        if c >= n:
            return c
    return p


def chunk_class(n: int, floor: int = 4096) -> int:
    """Morsel chunk-size quantizer: pow2 with a floor, so every chunk
    of a stream shares ONE static shape (exec/morsel.py) and the OOM
    downshift ladder (halving) stays inside the same quantized family.
    Coarser than size_class on purpose — a chunk is an ephemeral
    streaming window, not a resident table, so compile-class economy
    beats padding economy."""
    p = floor
    while p < n:
        p <<= 1
    return p


def lut_capacity(n: int, floor: int = 16) -> int:
    """Dictionary-LUT capacity quantizer (storage/codec.py): pow2 with
    a floor, so an append-only integer dictionary keeps ONE aux-array
    shape — and therefore one compiled-program class — until it
    doubles.  The codec analog of chunk_class: capacity is aval- and
    key-visible, so it must come from a quantized family."""
    p = floor
    while p < n:
        p <<= 1
    return p


def stage_padded(host_cols, sel):
    """Host column slices -> pow2-padded device arrays for one pass.
    `sel` is a slice (row-range slab), an int index array (hash
    partition / index lookup), or slice(None) for everything.  The
    shared device-staging tail of the spill, mesh, and index tiers."""
    import jax
    import numpy as np

    from ..utils.dtypes import stage_cast
    out = {}
    n = None
    for name, arr in host_cols.items():
        sub = stage_cast(arr[sel])
        if n is None:
            n = len(sub)
        padded = next_pow2(max(n, 1))
        buf = np.zeros((padded, *sub.shape[1:]), dtype=sub.dtype)
        buf[:n] = sub
        out[name] = jax.device_put(buf)
    return out, (n or 0)
