"""Native bulk loader binding — C++ parse loop via ctypes, pandas fallback.

Reference analog: commands/copy.c's C attribute parser.  The native library
is built on demand with g++ from native/loader.cpp (no pip/pybind — plain
ctypes over a C ABI); any failure falls back to the pandas C engine.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..catalog.schema import TableDef
from ..catalog.types import TypeKind
from ..utils import locks

_lock = locks.Lock("storage.loader._lock")
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "loader.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libotbloader.so")

_KIND = {TypeKind.INT32: 0, TypeKind.INT64: 0, TypeKind.FLOAT64: 1,
         TypeKind.DECIMAL: 2, TypeKind.DATE: 3, TypeKind.TEXT: 4,
         TypeKind.BOOL: 5}



# holding the lock across the (timeout-bounded, once-ever) g++ build is
# the point: concurrent first-callers must not race duplicate compiles
def _get_lib():  # otblint: disable=lock-blocking
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO)
            lib.otb_count_rows.restype = ctypes.c_longlong
            lib.otb_count_rows.argtypes = [ctypes.c_char_p]
            lib.otb_parse.restype = ctypes.c_longlong
            lib.otb_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_longlong]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _get_lib() is not None


def load_tbl(path: str, td: TableDef, columns: list[str],
             delimiter: str = "|") -> dict:
    """Parse a delimited file into raw column values keyed by column name
    (TEXT as numpy bytes arrays, DECIMAL as scaled storage ints, DATE as
    day numbers).  Uses the native parser when possible; transparently
    falls back to pandas otherwise (vectors, unbounded text, over-length
    values, missing compiler)."""
    out = _load_native(path, td, columns, delimiter)
    if out is None:
        # the native parser refuses backslashes (\N NULLs / escapes of
        # the COPY text format) along with its other unsupported inputs;
        # files carrying them take the escape-aware python path
        if _file_has_backslash(path):
            out = _load_text_escaped(path, td, columns, delimiter)
        else:
            out = _load_pandas(path, td, columns, delimiter)
    return out


def _file_has_backslash(path: str) -> bool:
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return False
            if b"\\" in chunk:
                return True


def _load_text_escaped(path: str, td: TableDef, columns: list[str],
                       delimiter: str) -> dict:
    """COPY text-format reader: honors backslash escapes and the \\N
    NULL marker (commands/copy.c CopyReadAttributesText analog; the
    slow path — only files containing backslashes come here)."""
    cols: dict[str, list] = {c: [] for c in columns}
    with open(path, "r") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            # split on UNESCAPED delimiters, keeping raw field text
            raw_fields, cur, esc = [], [], False
            for ch in line:
                if esc:
                    cur.append("\\" + ch)
                    esc = False
                elif ch == "\\":
                    esc = True
                elif ch == delimiter:
                    raw_fields.append("".join(cur))
                    cur = []
                else:
                    cur.append(ch)
            raw_fields.append("".join(cur))
            for c, raw in zip(columns, raw_fields):
                if raw == "\\N":
                    cols[c].append(None)
                    continue
                # unescape: \\ -> \, \n -> newline, \<d> -> d
                out, esc = [], False
                for ch in raw:
                    if esc:
                        out.append("\n" if ch == "n" else ch)
                        esc = False
                    elif ch == "\\":
                        esc = True
                    else:
                        out.append(ch)
                s = "".join(out)
                k = td.column(c).type.kind
                if k in (TypeKind.INT32, TypeKind.INT64):
                    cols[c].append(int(s))
                elif k == TypeKind.FLOAT64:
                    cols[c].append(float(s))
                elif k == TypeKind.BOOL:
                    cols[c].append(s.strip().lower() in
                                   ("t", "true", "1"))
                else:
                    cols[c].append(s)   # decimal/date/text: raw string
    return cols


def _load_pandas(path: str, td: TableDef, columns: list[str],
                 delimiter: str) -> dict:
    import pandas as pd
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    df = pd.read_csv(path, sep=delimiter, header=None,
                     names=columns + ["__trail"], index_col=False,
                     engine="c", na_values=["\\N"],
                     keep_default_na=False)
    if df["__trail"].isna().all():
        df = df.drop(columns="__trail")
    out = {}
    for c in columns:
        s = df[c]
        if s.isna().any():
            out[c] = [None if pd.isna(v) else v for v in s.tolist()]
        else:
            out[c] = s.tolist()
    return out


def _load_native(path: str, td: TableDef, columns: list[str],
                 delimiter: str = "|") -> Optional[dict]:
    lib = _get_lib()
    if lib is None:
        return None
    for c in columns:
        t = td.column(c).type
        if t.kind == TypeKind.VECTOR:
            return None   # vectors go through the python path
        if t.kind == TypeKind.TEXT and t.max_len <= 0:
            return None   # unbounded text: no fixed-width buffer
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    n = lib.otb_count_rows(path.encode())
    if n < 0:
        raise FileNotFoundError(path)
    ncols = len(columns)
    kinds = (ctypes.c_int * ncols)()
    scales = (ctypes.c_int * ncols)()
    outs = (ctypes.c_void_p * ncols)()
    bufs = {}
    for i, cname in enumerate(columns):
        t = td.column(cname).type
        kinds[i] = _KIND[t.kind]
        if t.kind == TypeKind.DECIMAL:
            scales[i] = t.scale
            buf = np.empty(n, dtype=np.int64)
        elif t.kind == TypeKind.TEXT:
            width = t.max_len
            scales[i] = width
            buf = np.zeros(n * width, dtype=np.uint8)
        elif t.kind == TypeKind.DATE:
            scales[i] = 0
            buf = np.empty(n, dtype=np.int32)
        elif t.kind == TypeKind.FLOAT64:
            scales[i] = 0
            buf = np.empty(n, dtype=np.float64)
        else:
            scales[i] = 0
            buf = np.empty(n, dtype=np.int64)
        bufs[cname] = buf
        outs[i] = buf.ctypes.data_as(ctypes.c_void_p)
    got = lib.otb_parse(path.encode(), delimiter.encode()[0:1][0] if
                        isinstance(delimiter, str) else delimiter,
                        ncols, kinds, scales, outs, n)
    if got < 0:
        # over-length text / malformed line: let the general path decide
        return None
    out = {}
    for i, cname in enumerate(columns):
        t = td.column(cname).type
        buf = bufs[cname]
        if t.kind == TypeKind.TEXT:
            width = t.max_len
            # keep as a numpy bytes array: the dictionary encoder uniques
            # it at C speed (per-string python decode would dominate)
            out[cname] = buf[:got * width].view(f"S{width}")
        elif t.kind == TypeKind.INT32:
            out[cname] = buf[:got].astype(np.int32)
        elif t.kind == TypeKind.BOOL:
            out[cname] = buf[:got].astype(np.bool_)
        else:
            out[cname] = buf[:got]
        if t.kind == TypeKind.DECIMAL:
            # already in scaled storage form: mark so encode skips rescale
            out[cname] = _PreScaled(out[cname])
    return out


class _PreScaled(np.ndarray):
    """Marker: decimal values already scaled to storage form."""
    def __new__(cls, arr):
        return np.asarray(arr).view(cls)
