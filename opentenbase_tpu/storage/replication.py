"""Datanode streaming replication: WAL shipping to a standby + promote.

Reference analog: src/backend/replication/walsender.c / walreceiver.c +
syncrep.c, scoped to this engine's redo-only logical WAL
(storage/wal.py): the primary ships every framed WAL record as it is
written, and ships its checkpoint artifacts (npz snapshots + catalog)
when it truncates the log — the standby's data directory is therefore
always a valid crash-image of the primary, and PROMOTE is exactly crash
recovery on that directory (the same rule GTM standby promotion uses,
gtm/standby.py).

Sync mode (the default, reference synchronous_commit=on under sync
standby): a failed ship raises out of Wal.append, so a commit is never
ACKNOWLEDGED that the standby hasn't durably received.  As in the
reference (syncrep.c waits after the local flush), the record is
locally durable before the ship — a crash may therefore recover an
unacknowledged transaction; acknowledged ones exist on both sides.
Async mode keeps serving and flags `standby_ok` False.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
from typing import Callable, Optional

from ..net.wire import recv_msg, send_msg
from ..obs import xray
from ..utils import locks
from .wal import Wal, decode_frame


class StandbyLag(Exception):
    """A standby's GTS high-water mark does not cover the requested
    snapshot — the coordinator's replica router falls through to the
    primary (reference: hot standby query conflict, except resolved by
    routing instead of by canceling the standby query)."""

    def __init__(self, msg: str, hwm: int = 0):
        super().__init__(msg)
        self.hwm = int(hwm)


class DnStandby:
    """Receives a primary's WAL stream + checkpoint artifacts into its
    own data directory.  `promote()` hands the directory to a normal
    recovery (DataNode.recover / LocalNode._recover replays it)."""

    def __init__(self, datadir: str):
        self.datadir = datadir
        os.makedirs(datadir, exist_ok=True)
        self._wal = open(os.path.join(datadir, "wal.log"), "ab")
        self._lock = locks.Lock("storage.replication.DnStandby._lock")
        self.records = 0

    def apply_wal(self, frame: bytes) -> None:
        """One framed (length+crc+blob) WAL record, verbatim."""
        with self._lock:
            self._wal.write(frame)
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self.records += 1

    def apply_checkpoint(self, files: dict[str, bytes]) -> None:
        """Checkpoint artifacts (table .ckpt npz files, catalog.json,
        meta.json) + WAL truncation — mirrors the primary's state at its
        checkpoint exactly."""
        with self._lock:
            for name, blob in files.items():
                safe = os.path.basename(name)
                tmp = os.path.join(self.datadir, safe + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, os.path.join(self.datadir, safe))
            self._wal.close()
            self._wal = open(os.path.join(self.datadir, "wal.log"), "wb")
            self._wal.flush()

    def close(self):
        with self._lock:
            self._wal.close()


class HotStandby(DnStandby):
    """A standby that ALSO keeps a live, queryable DataNode image — hot
    standby read scale-out (reference: hot_standby=on + walreceiver
    feedback).  A hot standby IS crash recovery running continuously:
    every shipped frame is decoded and applied through the exact same
    ``DataNode.apply_record`` path that replays the WAL after a crash,
    with the pending/in-doubt maps carried across frames instead of
    resolved at the end (an un-committed prepare just waits for its
    verdict frame).

    ``gts_hwm`` is the replica's GTS high-water mark: the newest commit
    timestamp applied (seeded from the primary's ``hwm.json`` checkpoint
    artifact, so a freshly attached replica starts caught-up).  The
    coordinator routes a snapshot read here only when the hwm covers
    every commit it has acknowledged on the primary.

    Reads and WAL apply serialize on ``_lock`` — one lock per replica is
    the scale-out unit: N replicas means N independent device pipelines
    instead of one."""

    def __init__(self, datadir: str, index: int = 0):
        super().__init__(datadir)
        # re-bound under the base class's canonical rank name so static
        # analysis can resolve `self._lock` in this class's methods (the
        # analyzer does not walk the MRO); same name = same graph node
        self._lock = locks.Lock("storage.replication.DnStandby._lock")
        self.index = index
        self._node = None            # guarded_by: _lock
        self._pending: dict = {}     # guarded_by: _lock
        self._gid_of: dict = {}      # guarded_by: _lock
        with self._lock:
            self._rebuild()

    # -- state rebuild (base backup / checkpoint boundary) --------------
    def _rebuild(self) -> None:
        """(Re)build the live node from the shipped checkpoint artifacts
        + any WAL frames received since.  Caller holds ``_lock``."""
        from types import SimpleNamespace
        from ..catalog.schema import TableDef
        from ..parallel.cluster import DataNode
        spath = os.path.join(self.datadir, "schema.json")
        if not os.path.exists(spath):
            self._node = None        # nothing shipped yet: cold
            return
        with open(spath) as f:
            tds = {name: TableDef.from_json(j)
                   for name, j in json.load(f).items()}
        old_hwm = self._node.last_commit_ts if self._node else 0
        node = DataNode(self.index, datadir=self.datadir)
        node.load_checkpoint(SimpleNamespace(tables=tds))
        hpath = os.path.join(self.datadir, "hwm.json")
        if os.path.exists(hpath):
            with open(hpath) as f:
                node.last_commit_ts = int(
                    json.load(f).get("gts_hwm", 0))
        self._pending, self._gid_of = {}, {}
        for rec in Wal.replay(os.path.join(self.datadir, "wal.log")):
            node.apply_record(rec, self._pending, self._gid_of)
        # monotonic across checkpoints: a rebuild never un-sees a commit
        node.last_commit_ts = max(node.last_commit_ts, old_hwm)
        self._node = node

    @property
    def gts_hwm(self) -> int:
        with self._lock:
            return self._node.last_commit_ts if self._node else -1

    # -- stream apply ---------------------------------------------------
    def apply_wal(self, frame: bytes) -> None:
        super().apply_wal(frame)     # durable first (promote still works)
        with self._lock:
            rec = decode_frame(frame)
            if rec is not None and self._node is not None:
                self._node.apply_record(rec, self._pending,
                                        self._gid_of)

    def apply_checkpoint(self, files: dict[str, bytes]) -> None:
        super().apply_checkpoint(files)
        with self._lock:
            self._rebuild()

    # -- the read surface (what the CN's replica router calls) ----------
    # snapshot-gate: hwm >= min_hwm
    def exec_plan(self, plan, snapshot_ts: int, txid: int, params: dict,
                  sources: dict, min_hwm: int = 0):
        """Run a read fragment against the replica image, refusing when
        the hwm does not cover ``min_hwm`` (the router falls through to
        the primary).  The lock hold spans the execution on purpose:
        apply and reads serialize per replica, and the GIL drops inside
        XLA compute, so N replicas scale N-ways."""
        from ..utils import snapcheck
        with self._lock:
            node = self._node
            hwm = node.last_commit_ts if node is not None else -1
            if node is None or hwm < min_hwm:
                raise StandbyLag(
                    f"standby hwm {hwm} < required {min_hwm}", hwm)
            if snapcheck.enabled() or snapcheck.history_on():
                snapcheck.serve(
                    "storage.replication.HotStandby.exec_plan",
                    snapshot_gts=snapshot_ts, entry_gts=min_hwm,
                    session=txid, source="standby")
            # may-acquire: exec.plancache._LOCK
            # may-acquire: storage.bufferpool._LOCK
            return node.exec_plan(plan, snapshot_ts, txid, params,
                                  sources)


class DnStandbyServer:
    """TCP front end for a DnStandby (the walreceiver process)."""

    def __init__(self, standby: DnStandby, host: str = "127.0.0.1",
                 port: int = 0):
        self.standby = standby
        sb = standby

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    if msg is None:
                        return
                    op = msg.get("op")
                    # standby reads carry CN trace context too: a
                    # routed read's server time shows up in the trace
                    sx = xray.server_span(msg, op or "",
                                          node="standby").open()
                    try:
                        if op == "wal":
                            sb.apply_wal(msg["frame"])
                            resp = {"ok": True, "records": sb.records}
                        elif op == "checkpoint":
                            sb.apply_checkpoint(msg["files"])
                            resp = {"ok": True}
                        elif op == "ping":
                            resp = {"pong": True, "records": sb.records}
                        elif op == "hwm":
                            # cold DnStandby has no hwm: AttributeError
                            # -> etype reply -> the router drops it from
                            # read rotation permanently
                            resp = {"ok": True, "hwm": sb.gts_hwm}
                        elif op == "exec_plan":
                            # snapshot-gate: msg["snapshot_ts"]
                            # (delegates: HotStandby.exec_plan
                            # re-checks hwm >= min_hwm itself)
                            out = sb.exec_plan(
                                msg["plan"], msg["snapshot_ts"],
                                msg["txid"], msg.get("params") or {},
                                msg.get("sources") or {},
                                min_hwm=msg.get("min_hwm", 0))
                            resp = {"ok": out, "hwm": sb.gts_hwm}
                        else:
                            resp = {"error": f"unknown op {op!r}"}
                    except Exception as e:
                        resp = {"error": str(e),
                                "etype": type(e).__name__}
                        if isinstance(e, StandbyLag):
                            resp["hwm"] = e.hwm
                    sx.close()
                    sx.attach(resp)
                    send_msg(self.request, resp)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class WalShip:
    """Primary-side shipping hooks: `frame(bytes)` per WAL record and
    `checkpoint(files)` per checkpoint.  One persistent connection,
    synchronous acks."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.addr = (host, port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = locks.Lock("storage.replication.WalShip._lock")

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                self.addr, timeout=self.timeout)
        return self._sock

    # the lock IS the ship serializer: WAL frames must arrive at the
    # standby in write order, so the conversation runs under it by
    # design; the hold is bounded by the socket timeout
    def _call(self, msg: dict) -> None:  # otblint: disable=lock-blocking
        xray.inject(msg)
        with self._lock:
            try:
                s = self._conn()
                # chaos point standby.ship; expect_reply: a standby
                # that hangs up while it owes an ack is a failed ship
                # (sync replication must not mistake it for success).
                # wait_event's enter/exit touch the wait register +
                # histograms (opaque to the callgraph):
                # may-acquire: obs.xray._WLOCK
                # may-acquire: obs.metrics.Registry._lock
                # may-acquire: obs.metrics.metric._lock
                with xray.wait_event("wal-ship"):
                    send_msg(s, msg, fault="standby.ship")
                    resp = recv_msg(s, expect_reply=True)
                xray.absorb(resp, node="standby", op=msg.get("op", ""))
            except (ConnectionError, OSError):
                try:
                    if self._sock is not None:
                        self._sock.close()
                finally:
                    self._sock = None
                raise
            if not resp.get("ok"):
                raise ConnectionError(f"standby rejected: {resp}")

    def frame(self, frame: bytes) -> None:
        self._call({"op": "wal", "frame": frame})

    def checkpoint(self, files: dict[str, bytes]) -> None:
        self._call({"op": "checkpoint", "files": files})

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


class FanoutShip:
    """One primary, N standbys: every frame/checkpoint replicates to all
    (reference: multiple walsenders off one WAL).  Sync semantics are
    all-or-error — a failed member raises out of the fan-out, so a sync
    commit is never acknowledged that any registered standby missed;
    members that already received the frame are simply ahead, which
    replication tolerates by design (an unacknowledged commit may exist
    on a standby, never the reverse)."""

    def __init__(self, ships: list):
        self.ships = list(ships)

    def add(self, ship) -> None:
        self.ships.append(ship)

    def frame(self, frame: bytes) -> None:
        for s in self.ships:
            s.frame(frame)

    def checkpoint(self, files: dict[str, bytes]) -> None:
        for s in self.ships:
            s.checkpoint(files)

    def close(self) -> None:
        for s in self.ships:
            s.close()


def checkpoint_files(datadir: str) -> dict[str, bytes]:
    """The artifacts a checkpoint must ship: every table snapshot plus
    catalog/meta and the hot-standby sidecars (table schemas + GTS
    high-water mark) — the pg_basebackup-lite set for this engine."""
    out = {}
    for name in os.listdir(datadir):
        if name.endswith(".ckpt") or name in ("catalog.json",
                                              "meta.json",
                                              "schema.json",
                                              "hwm.json"):
            with open(os.path.join(datadir, name), "rb") as f:
                out[name] = f.read()
    return out
