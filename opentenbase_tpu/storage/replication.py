"""Datanode streaming replication: WAL shipping to a standby + promote.

Reference analog: src/backend/replication/walsender.c / walreceiver.c +
syncrep.c, scoped to this engine's redo-only logical WAL
(storage/wal.py): the primary ships every framed WAL record as it is
written, and ships its checkpoint artifacts (npz snapshots + catalog)
when it truncates the log — the standby's data directory is therefore
always a valid crash-image of the primary, and PROMOTE is exactly crash
recovery on that directory (the same rule GTM standby promotion uses,
gtm/standby.py).

Sync mode (the default, reference synchronous_commit=on under sync
standby): a failed ship raises out of Wal.append, so a commit is never
ACKNOWLEDGED that the standby hasn't durably received.  As in the
reference (syncrep.c waits after the local flush), the record is
locally durable before the ship — a crash may therefore recover an
unacknowledged transaction; acknowledged ones exist on both sides.
Async mode keeps serving and flags `standby_ok` False.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from typing import Callable, Optional

from ..net.wire import recv_msg, send_msg
from ..utils import locks


class DnStandby:
    """Receives a primary's WAL stream + checkpoint artifacts into its
    own data directory.  `promote()` hands the directory to a normal
    recovery (DataNode.recover / LocalNode._recover replays it)."""

    def __init__(self, datadir: str):
        self.datadir = datadir
        os.makedirs(datadir, exist_ok=True)
        self._wal = open(os.path.join(datadir, "wal.log"), "ab")
        self._lock = locks.Lock("storage.replication.DnStandby._lock")
        self.records = 0

    def apply_wal(self, frame: bytes) -> None:
        """One framed (length+crc+blob) WAL record, verbatim."""
        with self._lock:
            self._wal.write(frame)
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self.records += 1

    def apply_checkpoint(self, files: dict[str, bytes]) -> None:
        """Checkpoint artifacts (table .ckpt npz files, catalog.json,
        meta.json) + WAL truncation — mirrors the primary's state at its
        checkpoint exactly."""
        with self._lock:
            for name, blob in files.items():
                safe = os.path.basename(name)
                tmp = os.path.join(self.datadir, safe + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, os.path.join(self.datadir, safe))
            self._wal.close()
            self._wal = open(os.path.join(self.datadir, "wal.log"), "wb")
            self._wal.flush()

    def close(self):
        with self._lock:
            self._wal.close()


class DnStandbyServer:
    """TCP front end for a DnStandby (the walreceiver process)."""

    def __init__(self, standby: DnStandby, host: str = "127.0.0.1",
                 port: int = 0):
        self.standby = standby
        sb = standby

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    if msg is None:
                        return
                    op = msg.get("op")
                    try:
                        if op == "wal":
                            sb.apply_wal(msg["frame"])
                            resp = {"ok": True, "records": sb.records}
                        elif op == "checkpoint":
                            sb.apply_checkpoint(msg["files"])
                            resp = {"ok": True}
                        elif op == "ping":
                            resp = {"pong": True, "records": sb.records}
                        else:
                            resp = {"error": f"unknown op {op!r}"}
                    except Exception as e:
                        resp = {"error": str(e)}
                    send_msg(self.request, resp)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class WalShip:
    """Primary-side shipping hooks: `frame(bytes)` per WAL record and
    `checkpoint(files)` per checkpoint.  One persistent connection,
    synchronous acks."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.addr = (host, port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = locks.Lock("storage.replication.WalShip._lock")

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                self.addr, timeout=self.timeout)
        return self._sock

    # the lock IS the ship serializer: WAL frames must arrive at the
    # standby in write order, so the conversation runs under it by
    # design; the hold is bounded by the socket timeout
    def _call(self, msg: dict) -> None:  # otblint: disable=lock-blocking
        with self._lock:
            try:
                s = self._conn()
                # chaos point standby.ship; expect_reply: a standby
                # that hangs up while it owes an ack is a failed ship
                # (sync replication must not mistake it for success)
                send_msg(s, msg, fault="standby.ship")
                resp = recv_msg(s, expect_reply=True)
            except (ConnectionError, OSError):
                try:
                    if self._sock is not None:
                        self._sock.close()
                finally:
                    self._sock = None
                raise
            if not resp.get("ok"):
                raise ConnectionError(f"standby rejected: {resp}")

    def frame(self, frame: bytes) -> None:
        self._call({"op": "wal", "frame": frame})

    def checkpoint(self, files: dict[str, bytes]) -> None:
        self._call({"op": "checkpoint", "files": files})

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


def checkpoint_files(datadir: str) -> dict[str, bytes]:
    """The artifacts a checkpoint must ship: every table snapshot plus
    catalog/meta (the pg_basebackup-lite set for this engine)."""
    out = {}
    for name in os.listdir(datadir):
        if name.endswith(".ckpt") or name in ("catalog.json",
                                              "meta.json"):
            with open(os.path.join(datadir, name), "rb") as f:
                out[name] = f.read()
    return out
