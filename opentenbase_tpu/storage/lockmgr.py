"""Row-lock wait management — one LockManager per datanode.

Reference analog: src/backend/storage/lmgr (XactLockTableWait: a txn
waiting on another txn's completion to acquire a tuple lock) plus the
distributed-deadlock machinery (utils/gdd/gdd_detector.c).

TPU-first framing: row locks never touch the device data plane.  A
conflict is discovered host-side during the (already host-side) DML
marking pass, and waiting is a host thread blocking on the holder's
commit/abort — the columnar batches and compiled programs stay lock-free.
Only write-write conflicts ever wait; readers never block (MVCC).

Wait edges (waiter txid -> holder txid) are exported per node; the
cluster-level GDD detector (parallel/gdd.py) unions them across
datanodes, finds cycles, and kills the youngest transaction in a cycle
— the reference's global wait-for-graph algorithm, without the
per-backend proclock scanning (gdd_detector.c builds the same graph
from pg_stat_activity + lock tables).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from ..obs import xray
from ..utils import locks


class LockTimeout(Exception):
    pass


class DeadlockDetected(Exception):
    pass


class LockNotAvailable(Exception):
    """FOR UPDATE NOWAIT hit a held lock."""


class LockManager:
    # remembered txn verdicts (bounded): a waiter that observed the
    # conflict just before the holder resolved still gets its answer
    _RESOLVED_KEEP = 8192

    def __init__(self):
        self._cond = locks.Condition(name="storage.lockmgr.LockManager._cond")
        self._resolved: OrderedDict[int, str] = OrderedDict()
        self._waits: dict[int, int] = {}      # waiter -> holder
        self._killed: set[int] = set()        # GDD victims

    # ---- txn lifecycle ----
    def resolve(self, txid: int, committed: bool):
        with self._cond:
            self._resolved[txid] = "committed" if committed \
                else "aborted"
            while len(self._resolved) > self._RESOLVED_KEEP:
                self._resolved.popitem(last=False)
            self._killed.discard(txid)
            self._cond.notify_all()

    def verdict(self, txid: int):
        with self._cond:
            return self._resolved.get(txid)

    # ---- GDD surface ----
    def wait_edges(self) -> dict[int, int]:
        with self._cond:
            return dict(self._waits)

    def kill(self, txid: int):
        """Mark a GDD victim: its own waits raise DeadlockDetected at
        the next wakeup (the victim's session then aborts normally,
        releasing its locks)."""
        with self._cond:
            self._killed.add(txid)
            self._cond.notify_all()

    # ---- the wait itself ----
    def wait_for(self, holder: int, waiter: int,
                 timeout: float) -> str:
        """Block until `holder` commits or aborts.  Returns 'committed'
        or 'aborted'; raises LockTimeout / DeadlockDetected.  A local
        wait cycle (both txns waiting on this node) is detected
        immediately; cross-node cycles are the GDD detector's job."""
        deadline = time.monotonic() + timeout
        with self._cond:
            h = holder
            seen = set()
            while h is not None and h not in seen:
                if h == waiter:
                    raise DeadlockDetected(
                        f"deadlock detected: txn {waiter} and txn "
                        f"{holder} wait on each other")
                seen.add(h)
                h = self._waits.get(h)
            self._waits[waiter] = holder
            try:
                while True:
                    if waiter in self._killed:
                        self._killed.discard(waiter)
                        raise DeadlockDetected(
                            "deadlock detected (distributed cycle; "
                            f"txn {waiter} chosen as victim)")
                    v = self._resolved.get(holder)
                    if v is not None:
                        return v
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise LockTimeout(
                            f"lock wait on txn {holder} timed out")
                    with xray.wait_event("lockmgr"):
                        self._cond.wait(min(remaining, 0.25))
            finally:
                self._waits.pop(waiter, None)
