"""opentenbase_tpu — a TPU-native distributed SQL (HTAP) framework.

A from-scratch rebuild of the capabilities of OpenTenBase (reference:
/root/reference, a Postgres-XL-derived CN/DN/GTM shared-nothing cluster),
re-architected for TPU:

- DataNode executor hot loops (scan/filter/project, hash join, hash agg,
  sort, expression evaluation — reference src/backend/executor/*) run as
  JAX/XLA kernels over columnar shard batches.
- Inter-datanode hash redistribution (reference FN data plane,
  src/backend/forward + postmaster/forwardsend.c) maps to XLA `all_to_all`
  over ICI via `jax.sharding.Mesh` + `shard_map`.
- The control plane (parser, catalog, planner, GTS timestamp oracle, 2PC)
  is host-side, mirroring the reference's CN/GTM roles.

Layout (≈ reference layer map, SURVEY.md §1):
- catalog/   type system + system catalog (ref src/backend/catalog, pgxc_*)
- storage/   columnar chunk store, WAL, checkpoints (ref src/backend/storage)
- sql/       lexer/parser/analyzer (ref src/backend/parser)
- plan/      logical+physical planner, FQS, distribution (ref optimizer, pgxc/plan)
- exec/      host-side fragment executor over device kernels (ref executor)
- ops/       JAX/Pallas kernel library (ref execExprInterp/nodeHash/nodeAgg hot loops)
- parallel/  shard map, locator, cluster 2PC, mesh collectives (ref
             pgxc/locator, forward, execRemote.c remote-2PC)
- gtm/       timestamp-oracle service (ref src/gtm); distributed MVCC
             (GTS visibility, ref access/transam + tqual.c) lives in
             storage/ + ops/kernels.py as fused scan kernels
- net/       control-plane RPC between CN/DN processes (ref pooler/pgxcnode)
- cli/       psql-analog shell + cluster ctl (ref src/bin, contrib/pgxc_ctl)
"""

# Select a live backend BEFORE any jax computation can run: if the axon
# TPU tunnel is wedged, the first jnp op in ANY process with the plugin
# registered blocks forever.  connect() probes in a subprocess (cached,
# cross-process) and falls back to CPU — a plain library consumer must
# never hang at import or first use.
from opentenbase_tpu.utils.backend import connect as _connect

_connect()

import jax  # noqa: E402

# The engine is a database: 64-bit keys (e.g. TPC-H orderkey at SF100 exceeds
# int32) and exact int64 decimal arithmetic are part of the storage contract.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
