"""TPC-H schema DDL in this engine's dialect (XC-style DISTRIBUTE BY —
reference grammar extension; co-location choices follow common OpenTenBase
TPC-H deployment: big tables sharded on their join keys, dimensions
replicated)."""

SCHEMA = """
create table region (
    r_regionkey integer primary key,
    r_name varchar(25),
    r_comment varchar(152)
) distribute by replication;

create table nation (
    n_nationkey integer primary key,
    n_name varchar(25),
    n_regionkey integer,
    n_comment varchar(152)
) distribute by replication;

create table supplier (
    s_suppkey bigint primary key,
    s_name varchar(25),
    s_address varchar(40),
    s_nationkey integer,
    s_phone varchar(15),
    s_acctbal decimal(15,2),
    s_comment varchar(101)
) distribute by shard(s_suppkey);

create table customer (
    c_custkey bigint primary key,
    c_name varchar(25),
    c_address varchar(40),
    c_nationkey integer,
    c_phone varchar(15),
    c_acctbal decimal(15,2),
    c_mktsegment varchar(10),
    c_comment varchar(117)
) distribute by shard(c_custkey);

create table part (
    p_partkey bigint primary key,
    p_name varchar(55),
    p_mfgr varchar(25),
    p_brand varchar(10),
    p_type varchar(25),
    p_size integer,
    p_container varchar(10),
    p_retailprice decimal(15,2),
    p_comment varchar(23)
) distribute by shard(p_partkey);

create table partsupp (
    ps_partkey bigint,
    ps_suppkey bigint,
    ps_availqty integer,
    ps_supplycost decimal(15,2),
    ps_comment varchar(199),
    primary key (ps_partkey, ps_suppkey)
) distribute by shard(ps_partkey);

create table orders (
    o_orderkey bigint primary key,
    o_custkey bigint,
    o_orderstatus varchar(1),
    o_totalprice decimal(15,2),
    o_orderdate date,
    o_orderpriority varchar(15),
    o_clerk varchar(15),
    o_shippriority integer,
    o_comment varchar(79)
) distribute by shard(o_orderkey);

create table lineitem (
    l_orderkey bigint,
    l_partkey bigint,
    l_suppkey bigint,
    l_linenumber integer,
    l_quantity decimal(15,2),
    l_extendedprice decimal(15,2),
    l_discount decimal(15,2),
    l_tax decimal(15,2),
    l_returnflag varchar(1),
    l_linestatus varchar(1),
    l_shipdate date,
    l_commitdate date,
    l_receiptdate date,
    l_shipinstruct varchar(25),
    l_shipmode varchar(10),
    l_comment varchar(44),
    primary key (l_orderkey, l_linenumber)
) distribute by shard(l_orderkey);
"""
