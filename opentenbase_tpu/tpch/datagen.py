"""TPC-H-shaped data generator.

Not the official dbgen (correctness tests compare this engine against a
pandas oracle *on the same generated data*, so bit-compatibility with dbgen
is unnecessary); row counts, column domains, value distributions and
cross-table relationships follow the spec closely enough that every one of
the 22 queries exercises its intended access pattern and selectivity.
Seeded and vectorized (numpy) so SF0.01 tests are instant and SF1+ bench
loads are fast.
"""

from __future__ import annotations

import numpy as np

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
              for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                        "DRUM")]
TYPE_SYLL1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYLL2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYLL3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
P_NAME_WORDS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
                "black", "blanched", "blue", "blush", "brown", "burlywood",
                "burnished", "chartreuse", "chiffon", "chocolate", "coral",
                "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
                "dim", "dodger", "drab", "firebrick", "floral", "forest",
                "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
                "honeydew", "hot", "hotpink", "indian", "ivory", "khaki"]
COMMENT_WORDS = ["carefully", "final", "deposits", "requests", "special",
                 "regular", "express", "furiously", "quickly", "silent",
                 "pending", "ironic", "even", "bold", "blithely", "accounts",
                 "packages", "theodolites", "Customer", "Complaints",
                 "unusual", "slyly", "asymptotes", "instructions"]

_EPOCH = np.datetime64("1970-01-01", "D")


def _days(iso: str) -> int:
    return int((np.datetime64(iso, "D") - _EPOCH).astype(np.int64))


STARTDATE = _days("1992-01-01")
ENDDATE = _days("1998-08-02")


def _comments(rng, n, nwords=5):
    w = rng.choice(COMMENT_WORDS, size=(n, nwords))
    return [" ".join(row) for row in w]


def generate(sf: float = 0.01, seed: int = 19980802) -> dict:
    """Returns {table: {column: np.ndarray|list}} (raw python/np values,
    ready for Session insert or .tbl writing)."""
    rng = np.random.default_rng(seed)
    out = {}

    out["region"] = {
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
        "r_comment": _comments(rng, 5),
    }
    out["nation"] = {
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": np.asarray([r for _, r in NATIONS], dtype=np.int64),
        "n_comment": _comments(rng, 25),
    }

    n_supp = max(int(10000 * sf), 20)
    sk = np.arange(1, n_supp + 1, dtype=np.int64)
    supp_nation = rng.integers(0, 25, n_supp)
    out["supplier"] = {
        "s_suppkey": sk,
        "s_name": [f"Supplier#{i:09d}" for i in sk],
        "s_address": _comments(rng, n_supp, 3),
        "s_nationkey": supp_nation.astype(np.int64),
        "s_phone": [f"{11+int(nk)}-{rng.integers(100,999)}-"
                    f"{rng.integers(100,999)}-{rng.integers(1000,9999)}"
                    for nk in supp_nation],
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
        "s_comment": _comments(rng, n_supp, 8),
    }

    n_cust = max(int(150000 * sf), 100)
    ck = np.arange(1, n_cust + 1, dtype=np.int64)
    cust_nation = rng.integers(0, 25, n_cust)
    out["customer"] = {
        "c_custkey": ck,
        "c_name": [f"Customer#{i:09d}" for i in ck],
        "c_address": _comments(rng, n_cust, 3),
        "c_nationkey": cust_nation.astype(np.int64),
        "c_phone": [f"{11+int(nk)}-{a}-{b}-{c}" for nk, a, b, c in zip(
            cust_nation, rng.integers(100, 999, n_cust),
            rng.integers(100, 999, n_cust), rng.integers(1000, 9999, n_cust))],
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
        "c_mktsegment": list(rng.choice(SEGMENTS, n_cust)),
        "c_comment": _comments(rng, n_cust, 8),
    }

    n_part = max(int(200000 * sf), 200)
    pk = np.arange(1, n_part + 1, dtype=np.int64)
    brand_m = rng.integers(1, 6, n_part)
    brand_n = rng.integers(1, 6, n_part)
    ptype = [f"{a} {b} {c}" for a, b, c in zip(
        rng.choice(TYPE_SYLL1, n_part), rng.choice(TYPE_SYLL2, n_part),
        rng.choice(TYPE_SYLL3, n_part))]
    pprice = np.round(90000 + (pk % 200901) / 10 + 100 * (pk % 1000), 2) / 100
    out["part"] = {
        "p_partkey": pk,
        "p_name": [" ".join(rng.choice(P_NAME_WORDS, 5)) for _ in range(n_part)],
        "p_mfgr": [f"Manufacturer#{m}" for m in brand_m],
        "p_brand": [f"Brand#{m}{n}" for m, n in zip(brand_m, brand_n)],
        "p_type": ptype,
        "p_size": rng.integers(1, 51, n_part).astype(np.int64),
        "p_container": list(rng.choice(CONTAINERS, n_part)),
        "p_retailprice": pprice,
        "p_comment": _comments(rng, n_part, 3),
    }

    # partsupp: 4 suppliers per part
    ps_pk = np.repeat(pk, 4)
    n_ps = len(ps_pk)
    ps_sk = ((ps_pk + (np.tile(np.arange(4), n_part)
                       * (n_supp // 4 + 1))) % n_supp) + 1
    out["partsupp"] = {
        "ps_partkey": ps_pk,
        "ps_suppkey": ps_sk.astype(np.int64),
        "ps_availqty": rng.integers(1, 10000, n_ps).astype(np.int64),
        "ps_supplycost": np.round(rng.uniform(1.00, 1000.00, n_ps), 2),
        "ps_comment": _comments(rng, n_ps, 8),
    }

    n_ord = max(int(1500000 * sf), 1000)
    ok = np.arange(1, n_ord + 1, dtype=np.int64) * 4 - 3  # sparse keys
    # dbgen never assigns orders to custkey % 3 == 0 (leaves 1/3 of
    # customers order-less — Q13/Q22 depend on this)
    o_ck = rng.integers(1, n_cust + 1, n_ord).astype(np.int64)
    o_ck = np.where(o_ck % 3 == 0, (o_ck % (n_cust - 1)) + 1, o_ck)
    o_ck = np.where(o_ck % 3 == 0, o_ck + 1, o_ck)
    o_date = rng.integers(STARTDATE, ENDDATE - 151, n_ord)
    out["orders"] = {
        "o_orderkey": ok,
        "o_custkey": o_ck,
        "o_orderstatus": ["F"] * n_ord,  # fixed below from lineitems
        "o_totalprice": np.zeros(n_ord),
        "o_orderdate": o_date.astype(np.int64),
        "o_orderpriority": list(rng.choice(PRIORITIES, n_ord)),
        "o_clerk": [f"Clerk#{i:09d}" for i in rng.integers(1, 1001, n_ord)],
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        "o_comment": _comments(rng, n_ord, 6),
    }

    # lineitem: 1..7 per order
    nlines = rng.integers(1, 8, n_ord)
    l_ok = np.repeat(ok, nlines)
    l_odate = np.repeat(o_date, nlines)
    n_li = len(l_ok)
    l_pk = rng.integers(1, n_part + 1, n_li).astype(np.int64)
    # supplier co-located with partsupp rows (one of the part's 4 suppliers)
    pick = rng.integers(0, 4, n_li)
    l_sk = ((l_pk + pick * (n_supp // 4 + 1)) % n_supp) + 1
    qty = rng.integers(1, 51, n_li).astype(np.int64)
    eprice = np.round(qty * pprice[l_pk - 1], 2)
    disc = rng.integers(0, 11, n_li) / 100.0
    tax = rng.integers(0, 9, n_li) / 100.0
    shipdate = l_odate + rng.integers(1, 122, n_li)
    commitdate = l_odate + rng.integers(30, 91, n_li)
    receiptdate = shipdate + rng.integers(1, 31, n_li)
    cutoff = _days("1995-06-17")
    returnflag = np.where(receiptdate <= cutoff,
                          rng.choice(["R", "A"], n_li), "N")
    linestatus = np.where(shipdate > cutoff, "O", "F")
    linenumber = (np.arange(n_li, dtype=np.int64)
                  - np.repeat(np.cumsum(nlines) - nlines, nlines)) + 1
    out["lineitem"] = {
        "l_orderkey": l_ok,
        "l_partkey": l_pk,
        "l_suppkey": l_sk.astype(np.int64),
        "l_linenumber": linenumber,
        "l_quantity": qty.astype(np.float64),
        "l_extendedprice": eprice,
        "l_discount": disc,
        "l_tax": tax,
        "l_returnflag": list(returnflag),
        "l_linestatus": list(linestatus),
        "l_shipdate": shipdate.astype(np.int64),
        "l_commitdate": commitdate.astype(np.int64),
        "l_receiptdate": receiptdate.astype(np.int64),
        "l_shipinstruct": list(rng.choice(INSTRUCTS, n_li)),
        "l_shipmode": list(rng.choice(SHIPMODES, n_li)),
        "l_comment": _comments(rng, n_li, 4),
    }

    # orders derived columns
    import pandas as pd
    li = pd.DataFrame({"ok": l_ok, "price": eprice, "ls": linestatus})
    tot = li.groupby("ok")["price"].sum()
    all_f = li.assign(isf=(li.ls == "F")).groupby("ok")["isf"].agg(
        ["sum", "count"])
    status = np.where(all_f["sum"] == all_f["count"], "F",
                      np.where(all_f["sum"] == 0, "O", "P"))
    out["orders"]["o_totalprice"] = np.round(
        tot.reindex(ok).fillna(0).to_numpy(), 2)
    st = pd.Series(status, index=all_f.index).reindex(ok).fillna("O")
    out["orders"]["o_orderstatus"] = list(st.to_numpy())
    return out


def to_date_strings(table: dict, date_cols: list[str]) -> dict:
    """Convert int day columns to ISO strings (for .tbl files / inserts)."""
    out = dict(table)
    for c in date_cols:
        out[c] = [str(_EPOCH + np.timedelta64(int(v), "D"))
                  for v in table[c]]
    return out


DATE_COLS = {
    "orders": ["o_orderdate"],
    "lineitem": ["l_shipdate", "l_commitdate", "l_receiptdate"],
}


def load_into(session, data: dict):
    """Bulk-load generated data through the session's insert path."""
    for tname in ("region", "nation", "supplier", "customer", "part",
                  "partsupp", "orders", "lineitem"):
        tbl = data[tname]
        td = session.node.catalog.table(tname)
        st = session.node.stores[tname]
        n = len(next(iter(tbl.values())))
        session._insert_rows(td, st, tbl, n)


def as_dataframes(data: dict):
    """pandas view (dates as ints = days since epoch) for oracle queries."""
    import pandas as pd
    return {t: pd.DataFrame(cols) for t, cols in data.items()}
