"""Bound query trees (analyzer output, planner input).

Reference analog: the Query struct produced by parse analysis
(src/backend/parser/analyze.c, include/nodes/parsenodes.h Query) — range
table + jointree + targetlist of typed expressions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..catalog.schema import TableDef
from ..catalog.types import SqlType
from . import exprs as E


@dataclasses.dataclass
class RTE:
    """Range-table entry."""
    alias: str
    kind: str                             # 'table' | 'subquery'
    table: Optional[TableDef] = None
    subquery: Optional["BoundQuery"] = None
    # visible columns: plain name -> (qualified name, type)
    columns: dict[str, tuple[str, SqlType]] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class JoinStep:
    """One step of a left-deep join sequence: join `rte_index` to the
    accumulated left side.  kind 'inner' quals live in BoundQuery.where;
    outer-join quals stay here."""
    rte_index: int
    kind: str                             # 'inner' | 'left' | 'right' | 'cross'
    on: Optional[E.Expr] = None


@dataclasses.dataclass(frozen=True)
class SubLink(E.Expr):
    """Bound subquery expression embedded in a scalar context.
    link_kind: 'scalar' | 'exists' | 'in' | 'any' | 'all'
    """
    link_kind: str
    query: "BoundQuery"
    test_expr: Optional[E.Expr] = None     # for in/any/all: outer-side expr
    cmp_op: str = "="
    negated: bool = False

    def __post_init__(self):
        from ..catalog.types import BOOL
        t = BOOL if self.link_kind != "scalar" \
            else self.query.targets[0][1].type
        object.__setattr__(self, "type", t)

    def children(self):
        return (self.test_expr,) if self.test_expr is not None else ()

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


@dataclasses.dataclass
class BoundSetOp:
    """UNION [ALL] chain (transformSetOperationStmt analog)."""
    op: str
    all: bool
    left: object                   # BoundQuery | BoundSetOp
    right: object
    target_names: list[str]
    target_types: list[SqlType]
    order_by: list[tuple[int, bool]] = dataclasses.field(
        default_factory=list)      # (output column index, desc)
    limit: Optional[int] = None
    offset: int = 0


@dataclasses.dataclass
class BoundQuery:
    rtable: list[RTE]
    join_order: list[JoinStep]            # left-deep sequence over rtable
    where: list[E.Expr]                   # conjunct list (inner-join quals in)
    targets: list[tuple[str, E.Expr]]     # output name -> expr (may hold Agg)
    group_by: list[E.Expr]
    having: list[E.Expr]
    order_by: list[tuple[E.Expr, bool]]   # (expr, desc)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    correlated_cols: list[str] = dataclasses.field(default_factory=list)
    # qualified outer-scope column names this (sub)query references

    @property
    def has_aggs(self) -> bool:
        return bool(self.group_by) or any(
            E.contains_agg(e) for _, e in self.targets) or bool(self.having)
