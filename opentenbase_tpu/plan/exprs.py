"""Typed expression trees.

Reference analog: PostgreSQL's Expr nodes (src/include/nodes/primnodes.h)
compiled at ExecInitExpr time into the EEOP_* opcode program interpreted by
`ExecInterpExpr` (src/backend/executor/execExprInterp.c:14-41) or JITed by
LLVM (src/backend/jit/llvm/llvmjit_expr.c).  In this rebuild the opcode
interpreter AND the LLVM tier collapse into one thing: expressions compile to
jax-traceable closures that XLA fuses into the surrounding scan kernel
(exec/expr_compile.py).

Type/scale discipline for DECIMAL (scaled int64):
- add/sub/compare: operands rescaled to the larger scale
- mul: result scale = s1 + s2 (per-row products stay well inside int64)
- div: lowered to FLOAT64
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..catalog.types import (BOOL, FLOAT64, INT32, INT64, SqlType, TypeKind,
                             decimal as decimal_t)


class ExprError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class Expr:
    """Base: every node carries its result SqlType in `.type`."""
    type: SqlType = dataclasses.field(init=False, default=INT64)

    def children(self) -> Sequence["Expr"]:
        return ()


def _fields(**kw):
    return kw


@dataclasses.dataclass(frozen=True)
class Col(Expr):
    name: str
    col_type: SqlType

    def __post_init__(self):
        object.__setattr__(self, "type", self.col_type)


@dataclasses.dataclass(frozen=True)
class Lit(Expr):
    """Literal already in storage representation (scaled int for DECIMAL,
    days for DATE).  TEXT literals never appear here — string predicates are
    resolved against dictionaries at compile time (StrPred).  value=None is
    the SQL NULL literal (reference: Const.constisnull, primnodes.h)."""
    value: object
    lit_type: SqlType

    def __post_init__(self):
        object.__setattr__(self, "type", self.lit_type)

    @property
    def is_null(self) -> bool:
        return self.value is None


_NUM_RANK = {TypeKind.INT32: 0, TypeKind.INT64: 1, TypeKind.DECIMAL: 2,
             TypeKind.FLOAT64: 3}


def _common_numeric(a: SqlType, b: SqlType) -> SqlType:
    if not (a.is_numeric and b.is_numeric):
        raise ExprError(f"non-numeric operands {a} {b}")
    if TypeKind.FLOAT64 in (a.kind, b.kind):
        return FLOAT64
    if TypeKind.DECIMAL in (a.kind, b.kind):
        return decimal_t(30, max(a.scale, b.scale))
    if TypeKind.INT64 in (a.kind, b.kind):
        return INT64
    return INT32


@dataclasses.dataclass(frozen=True)
class Arith(Expr):
    op: str  # + - * /
    left: Expr
    right: Expr

    def __post_init__(self):
        a, b = self.left.type, self.right.type
        if self.op == "/":
            t = FLOAT64
        elif self.op == "%":
            if TypeKind.DECIMAL in (a.kind, b.kind) or \
                    TypeKind.FLOAT64 in (a.kind, b.kind):
                raise ExprError("modulo requires integer operands")
            t = _common_numeric(a, b)
        elif self.op == "*" and TypeKind.DECIMAL in (a.kind, b.kind) \
                and TypeKind.FLOAT64 not in (a.kind, b.kind):
            t = decimal_t(30, a.scale + b.scale)
        else:
            t = _common_numeric(a, b)
        object.__setattr__(self, "type", t)

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class Neg(Expr):
    arg: Expr

    def __post_init__(self):
        object.__setattr__(self, "type", self.arg.type)

    def children(self):
        return (self.arg,)


@dataclasses.dataclass(frozen=True)
class Cmp(Expr):
    op: str  # = <> < <= > >=
    left: Expr
    right: Expr

    def __post_init__(self):
        object.__setattr__(self, "type", BOOL)

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class BoolOp(Expr):
    op: str  # and | or
    args: tuple[Expr, ...]

    def __post_init__(self):
        object.__setattr__(self, "type", BOOL)

    def children(self):
        return self.args


@dataclasses.dataclass(frozen=True)
class Not(Expr):
    arg: Expr

    def __post_init__(self):
        object.__setattr__(self, "type", BOOL)

    def children(self):
        return (self.arg,)


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    whens: tuple[tuple[Expr, Expr], ...]
    else_: Optional[Expr]
    case_type: SqlType

    def __post_init__(self):
        object.__setattr__(self, "type", self.case_type)

    def children(self):
        out = []
        for c, v in self.whens:
            out += [c, v]
        if self.else_ is not None:
            out.append(self.else_)
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    """value IN (numeric literals) — storage-representation values."""
    arg: Expr
    values: tuple

    def __post_init__(self):
        object.__setattr__(self, "type", BOOL)

    def children(self):
        return (self.arg,)


@dataclasses.dataclass(frozen=True)
class TextExpr(Expr):
    """A TEXT-valued expression: an underlying dictionary-coded column with
    pure string->string transforms (e.g. substring) applied *to the
    dictionary*, not the rows — codes pass through unchanged, the decode
    table changes.  This is how substring(c_phone from 1 for 2) (TPC-H Q22)
    stays an integer column on device."""
    col: Col
    transforms: tuple = ()   # (("substring", start, length|None), ...)

    def __post_init__(self):
        object.__setattr__(self, "type", self.col.col_type)

    def children(self):
        return (self.col,)

    def apply(self, s: str) -> str:
        for t in self.transforms:
            if t[0] == "substring":
                start, length = t[1], t[2]
                lo = start - 1          # SQL positions are 1-based;
                if length is None:      # clip at the string start like PG
                    s = s[max(lo, 0):]
                else:
                    s = s[max(lo, 0):max(lo + length, 0)]
            else:
                raise ExprError(f"unknown text transform {t[0]}")
        return s


@dataclasses.dataclass(frozen=True)
class StrPred(Expr):
    """A predicate over a TEXT column (possibly transformed), described
    abstractly; the compiler resolves it against the store's dictionary into
    a device code-set mask.
    kind: 'eq' | 'ne' | 'like' | 'not_like' | 'in' | 'not_in' | 'lt' | 'le' |
    'gt' | 'ge'
    """
    col: Expr                 # Col or TextExpr over a TEXT column
    kind: str
    patterns: tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "type", BOOL)

    def children(self):
        return (self.col,)


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    """expr IS [NOT] NULL — non-strict: consumes the null mask, never
    produces one (reference: ExecEvalNullTest, execExprInterp.c)."""
    arg: Expr
    negated: bool = False

    def __post_init__(self):
        object.__setattr__(self, "type", BOOL)

    def children(self):
        return (self.arg,)


@dataclasses.dataclass(frozen=True)
class Coalesce(Expr):
    """COALESCE(a, b, ...) — first non-null argument (non-strict)."""
    args: tuple[Expr, ...]
    out_type: SqlType

    def __post_init__(self):
        object.__setattr__(self, "type", self.out_type)

    def children(self):
        return self.args


@dataclasses.dataclass(frozen=True)
class NullIf(Expr):
    """NULLIF(a, b): NULL when a = b, else a."""
    left: Expr
    right: Expr

    def __post_init__(self):
        object.__setattr__(self, "type", self.left.type)

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class DistExpr(Expr):
    """Vector distance: col <metric> constant-query (pgvector's
    <-> / <=> / <#> operators).  type FLOAT64."""
    metric: str              # 'l2' | 'cosine' | 'ip'
    col: Col                 # VECTOR column
    query: tuple             # query vector as a tuple of floats

    def __post_init__(self):
        object.__setattr__(self, "type", FLOAT64)

    def children(self):
        return (self.col,)


@dataclasses.dataclass(frozen=True)
class Extract(Expr):
    """EXTRACT(field FROM date) -> INT32.  field: year|month|day."""
    field: str
    arg: Expr

    def __post_init__(self):
        object.__setattr__(self, "type", INT32)

    def children(self):
        return (self.arg,)


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    to: SqlType

    def __post_init__(self):
        object.__setattr__(self, "type", self.to)

    def children(self):
        return (self.arg,)


# ---------------------------------------------------------------------------
# aggregates (consumed by the Agg operator, not by the row-wise compiler)
# ---------------------------------------------------------------------------

AGG_FUNCS = ("sum", "count", "avg", "min", "max")

WINDOW_FUNCS = ("row_number", "rank", "dense_rank", "lag",
                "lead", "first_value", "last_value") + AGG_FUNCS


@dataclasses.dataclass(frozen=True)
class WindowCall(Expr):
    """func(arg) OVER (PARTITION BY ... ORDER BY ... [frame]) — consumed
    by the Window operator (reference: WindowFunc + nodeWindowAgg.c).
    With an ORDER BY and no explicit frame, aggregate functions use the
    SQL default frame (RANGE UNBOUNDED PRECEDING..CURRENT ROW): running
    values, peers equal.  frame = (mode, (kind, n), (kind, n)) parsed
    from ROWS/RANGE BETWEEN (gram.y frame_clause)."""
    func: str
    arg: Optional[Expr]
    partition: tuple[Expr, ...]
    order: tuple[tuple[Expr, bool], ...]   # (expr, desc)
    offset: int = 1                        # lag/lead row offset
    default: Optional[Expr] = None         # lag/lead: None = SQL NULL
    frame: Optional[tuple] = None

    def __post_init__(self):
        if self.func not in WINDOW_FUNCS:
            raise ExprError(f"unknown window function {self.func}")
        if self.func in ("row_number", "rank", "dense_rank"):
            t = INT64
        elif self.func == "count":
            t = INT64
        elif self.func == "avg":
            t = FLOAT64
        else:
            t = self.arg.type
        object.__setattr__(self, "type", t)

    def children(self):
        out = list(self.partition) + [e for e, _ in self.order]
        if self.arg is not None:
            out.append(self.arg)
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class AggCall(Expr):
    func: str                  # sum|count|avg|min|max
    arg: Optional[Expr]        # None for count(*)
    distinct: bool = False

    def __post_init__(self):
        if self.func not in AGG_FUNCS:
            raise ExprError(f"unknown aggregate {self.func}")
        if self.func == "count":
            t = INT64
        elif self.func == "avg":
            t = FLOAT64
        else:
            t = self.arg.type
        object.__setattr__(self, "type", t)

    def children(self):
        return (self.arg,) if self.arg is not None else ()


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def contains_agg(e: Expr) -> bool:
    return any(isinstance(x, AggCall) for x in walk(e))
