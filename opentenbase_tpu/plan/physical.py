"""Physical plan nodes.

Reference analog: the Plan node tree of include/nodes/plannodes.h (SeqScan,
HashJoin, Agg, Sort, Limit ...) plus the XC additions RemoteSubplan /
RemoteQuery (include/pgxc/planner.h).  Differences by design:

- Operators consume/produce whole columnar batches, not tuples.
- There is no separate Hash node: the join's build side is its right child.
- Exchange operators (Redistribute/Broadcast/Gather) are the RemoteSubplan
  analog: they mark fragment boundaries for the distributed executor and map
  onto XLA collectives (all_to_all / all_gather / device->host).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..catalog.schema import TableDef
from . import exprs as E


@dataclasses.dataclass
class PhysNode:
    def children(self) -> list["PhysNode"]:
        return []

    def title(self) -> str:
        return type(self).__name__


@dataclasses.dataclass
class SeqScan(PhysNode):
    """Fused scan+visibility+filter+project over a table's chunks.
    Reference: ExecSeqScan + ExecQual/ExecProject (execScan.c) — one kernel
    here."""
    table: TableDef
    alias: str
    filters: list[E.Expr]
    # output qualified-name -> expr over the table's columns; None = all cols
    outputs: Optional[list[tuple[str, E.Expr]]] = None

    def title(self):
        f = f" filter={len(self.filters)}" if self.filters else ""
        return f"SeqScan {self.table.name} as {self.alias}{f}"


@dataclasses.dataclass
class Filter(PhysNode):
    child: PhysNode = None
    quals: list[E.Expr] = dataclasses.field(default_factory=list)

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Project(PhysNode):
    child: PhysNode = None
    outputs: list[tuple[str, E.Expr]] = dataclasses.field(default_factory=list)

    def children(self):
        return [self.child]


@dataclasses.dataclass
class HashJoin(PhysNode):
    """Equi-join; right child is the build side.  kind:
    inner|left|semi|anti.  Multi-key joins hash-combine with a residual
    equality recheck (reference nodeHashjoin.c keeps hashes + recheck too).
    Reference: ExecHashJoin (nodeHashjoin.c) over a chained hash table;
    here sort+searchsorted (ops/kernels.py join_*)."""
    left: PhysNode = None
    right: PhysNode = None
    left_keys: list[E.Expr] = dataclasses.field(default_factory=list)
    right_keys: list[E.Expr] = dataclasses.field(default_factory=list)
    kind: str = "inner"
    residual: list[E.Expr] = dataclasses.field(default_factory=list)

    def children(self):
        return [self.left, self.right]

    def title(self):
        return f"HashJoin {self.kind} on {len(self.left_keys)} key(s)"


@dataclasses.dataclass
class Agg(PhysNode):
    """Grouped aggregation.  mode: 'single' | 'partial' | 'final' —
    partial/final split mirrors RemoteQuery.rq_finalise_aggs
    (include/pgxc/planner.h:135)."""
    child: PhysNode = None
    group_keys: list[tuple[str, E.Expr]] = dataclasses.field(
        default_factory=list)
    aggs: list[tuple[str, E.AggCall]] = dataclasses.field(default_factory=list)
    mode: str = "single"

    def children(self):
        return [self.child]

    def title(self):
        return (f"Agg {self.mode} keys={len(self.group_keys)} "
                f"aggs={len(self.aggs)}")


@dataclasses.dataclass
class Sort(PhysNode):
    child: PhysNode = None
    keys: list[tuple[E.Expr, bool]] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None      # top-k fusion

    def children(self):
        return [self.child]

    def title(self):
        lim = f" limit={self.limit}" if self.limit is not None else ""
        return f"Sort keys={len(self.keys)}{lim}"


@dataclasses.dataclass
class Limit(PhysNode):
    child: PhysNode = None
    count: Optional[int] = None
    offset: int = 0

    def children(self):
        return [self.child]


# ---- exchange operators (fragment boundaries; reference RemoteSubplan) ----

@dataclasses.dataclass
class Redistribute(PhysNode):
    """Hash-redistribute rows across datanodes by key — the reference's
    RemoteSubplan with distributionType=HASH streaming FnPages
    (execFragment.c FragmentRedistributeData); on TPU one all_to_all."""
    child: PhysNode = None
    keys: list[E.Expr] = dataclasses.field(default_factory=list)

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Broadcast(PhysNode):
    """Replicate child output to all datanodes (FragmentSendTupleBroadcast
    analog; all_gather on TPU)."""
    child: PhysNode = None

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Gather(PhysNode):
    """Collect child output on the coordinator (device->host stream)."""
    child: PhysNode = None
    sort_keys: list[tuple[E.Expr, bool]] = dataclasses.field(
        default_factory=list)   # merge-sorted gather (SimpleSort analog)
    one: bool = False           # replicated child: read a single node
    limit: Optional[int] = None  # per-DN top-k cut before shipping

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Append(PhysNode):
    """Concatenate children with positionally-aligned columns (set ops,
    partition append — reference nodeAppend.c)."""
    inputs: list[PhysNode] = dataclasses.field(default_factory=list)

    def children(self):
        return list(self.inputs)


@dataclasses.dataclass
class IndexScan(PhysNode):
    """Point/range scan through a btree-equivalent sorted index
    (reference: nbtree + ExecIndexScan): host binary search selects the
    candidate rows, only those stage to device; the full filter list
    re-verifies on the staged subset (bounds are a pre-selection)."""
    table: object = None
    alias: str = ""
    key_col: str = ""          # plain column name
    lo: object = None          # storage-representation bounds
    hi: object = None
    lo_strict: bool = False
    hi_strict: bool = False
    filters: list = dataclasses.field(default_factory=list)
    outputs: list = dataclasses.field(default_factory=list)

    def title(self):
        return f"IndexScan {self.table.name} as {self.alias} " \
               f"key={self.key_col}"


@dataclasses.dataclass
class Window(PhysNode):
    """Window-function computation: adds one column per call, rows
    pass through (reference: nodeWindowAgg.c — sorted partitions,
    per-frame aggregation; here sort + segment scans in one kernel)."""
    child: Optional[PhysNode] = None
    calls: list = dataclasses.field(default_factory=list)
    # [(output name, E.WindowCall)]

    def children(self):
        return [self.child]

    def title(self):
        return f"Window calls={len(self.calls)}"


@dataclasses.dataclass
class SetOp(PhysNode):
    """INTERSECT / EXCEPT [ALL] over two positionally-aligned inputs
    (reference: nodeSetOp.c — hashed set-op counting per input side)."""
    inputs: list[PhysNode] = dataclasses.field(default_factory=list)
    op: str = "intersect"          # 'intersect' | 'except'
    all: bool = False
    names: list[str] = dataclasses.field(default_factory=list)
    types: list = dataclasses.field(default_factory=list)

    def children(self):
        return list(self.inputs)

    def title(self):
        return f"SetOp {self.op}{' all' if self.all else ''}"


@dataclasses.dataclass
class AnnSearch(PhysNode):
    """Top-k nearest-neighbor scan over a VECTOR column (pgvector's
    `ORDER BY vec <-> q LIMIT k` IVFFlat/seq path as one fused node)."""
    table: TableDef = None
    alias: str = ""
    filters: list[E.Expr] = dataclasses.field(default_factory=list)
    outputs: list[tuple[str, E.Expr]] = dataclasses.field(
        default_factory=list)
    vec_col: str = ""            # qualified column name
    metric: str = "l2"
    query: tuple = ()
    k: int = 10
    dist_name: str = "__dist"    # emitted distance column

    def title(self):
        return (f"AnnSearch {self.table.name} {self.metric} "
                f"k={self.k}")


@dataclasses.dataclass
class Result(PhysNode):
    """Constant/empty-input result (SELECT without FROM)."""
    outputs: list[tuple[str, E.Expr]] = dataclasses.field(default_factory=list)


def explain(node: PhysNode, indent: int = 0, out: Optional[list] = None,
            annotate=None) -> str:
    """Render a plan tree.  ``annotate(node) -> str`` (optional)
    appends per-node text — EXPLAIN ANALYZE actual rows/timings."""
    top = out is None
    if out is None:
        out = []
    extra = annotate(node) if annotate is not None else ""
    out.append("  " * indent + ("-> " if indent else "")
               + node.title() + (extra or ""))
    for c in node.children():
        if c is not None:
            explain(c, indent + 1, out, annotate)
    return "\n".join(out) if top else ""
