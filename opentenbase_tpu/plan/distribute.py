"""Distributed planning: annotate a physical plan with row distributions,
insert exchange operators where they mismatch, split into fragments.

Reference analog: every Path carries a Distribution
(include/nodes/relation.h:33-46); joins pick colocated/redistributed/
replicated strategies (optimizer/util/pathnode.c:4575
set_joinpath_distribution); redistribute_path/create_remotesubplan_path
insert exchanges (pathnode.c:2449,1851); aggregates split partial/final
(RemoteQuery.rq_finalise_aggs, include/pgxc/planner.h:135); the executor
cuts the tree at exchange boundaries into fragments
(execFragment.c:558 ExecInitFragmentTree).

FQS (fast query shipping) lives in fqs_target_node(): whole-query
single-node shipping when dist-key equality pins every sharded table to one
datanode (pgxc_FQS_planner, pgxc/plan/planner.c:390 +
pgxc_is_query_shippable, pgxcship.c:2431).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..catalog.catalog import Catalog
from ..catalog.schema import DistType
from ..parallel.locator import Locator
from . import exprs as E
from . import physical as P
from .planner import PlannedStmt, expr_cols
from .query import BoundQuery, SubLink


@dataclasses.dataclass
class Dist:
    kind: str                    # 'sharded' | 'replicated' | 'cn'
    keys: tuple[str, ...] = ()   # qualified cols rows are hash-placed by
    # () with kind='sharded' = partitioned by unknown key
    # node group owning the placement: alignment optimizations only
    # apply within one group's shard map (reference: pgxc_group)
    group: str = "default_group"


@dataclasses.dataclass
class ExchangeRef(P.PhysNode):
    """Fragment-input leaf: the output of exchange `index` for this node."""
    index: int = 0
    types: dict = dataclasses.field(default_factory=dict)

    def title(self):
        return f"ExchangeRef #{self.index}"


@dataclasses.dataclass
class BatchSource(P.PhysNode):
    """Executor-injected leaf holding a ready batch."""
    batch: object = None

    def title(self):
        return "BatchSource"


@dataclasses.dataclass
class Fragment:
    index: int
    plan: P.PhysNode
    location: str                 # 'dn' | 'cn'
    # exchange feeding this fragment's parent: set on edges below


@dataclasses.dataclass
class Exchange:
    index: int
    kind: str                     # 'redistribute' | 'broadcast' | 'gather'
    keys: list[E.Expr]
    source_fragment: int
    sort_keys: list = dataclasses.field(default_factory=list)
    limit: object = None          # per-DN top-k cut (gather only)


@dataclasses.dataclass
class DistPlan:
    fragments: list[Fragment]
    exchanges: list[Exchange]
    top_fragment: int
    init_plans: list
    output_names: list[str]
    fqs_node: Optional[int] = None     # set => whole plan runs on one DN
    via_gidx: str = ""                 # global index(es) that pinned it


def _subtree_est(node) -> Optional[float]:
    """Worst-case row estimate of a fragment subtree from its scan
    estimates (set by the planner from ANALYZE stats); None = unknown."""
    ests = []
    stack = [node]
    while stack:
        nd = stack.pop()
        if isinstance(nd, (P.SeqScan, P.IndexScan)):
            e = getattr(nd, "est_rows", None)
            if e is None:
                return None
            ests.append(float(e))
        for attr in ("child", "left", "right"):
            c = getattr(nd, attr, None)
            if isinstance(c, P.PhysNode):
                stack.append(c)
    if not ests:
        return None
    out = 1.0
    for e in ests:
        out *= max(e, 1.0)
    return out


# ---------------------------------------------------------------------------
# FQS analysis
# ---------------------------------------------------------------------------

def _has_sublinks(bq: BoundQuery) -> bool:
    for _, e in bq.targets:
        if any(isinstance(x, SubLink) for x in E.walk(e)):
            return True
    for q in bq.where:
        if any(isinstance(x, SubLink) for x in E.walk(q)):
            return True
    return False


def dist_key_pins(rte, where, allow_params: bool = False):
    """The `dist col = <pin>` conjuncts for one range-table entry, or
    None when not every dist col is pinned.  A pin is an E.Lit (point
    routing canonicalizes it to the representation bulk routing used),
    or — with allow_params — a '__bindparam' column name resolved at
    EXECUTE time.  Shared by plain FQS, prepared-statement FQS, and
    global-index routing so the three can never disagree."""
    dist_cols = [f"{rte.alias}.{c}"
                 for c in rte.table.distribution.dist_cols]
    values = {}
    for q in where:
        if isinstance(q, E.Cmp) and q.op == "=" \
                and isinstance(q.left, E.Col) \
                and q.left.name in dist_cols:
            if isinstance(q.right, E.Lit):
                values[q.left.name] = q.right
            elif allow_params and isinstance(q.right, E.Col) \
                    and q.right.name.startswith("__bindparam"):
                values[q.left.name] = q.right.name
    if set(values) != set(dist_cols):
        return None
    return [values[c] for c in dist_cols]


def fqs_target_node(bq: BoundQuery, catalog: Catalog) -> Optional[int]:
    """Single datanode that can answer the whole query, or None.

    Shippable when every sharded table is pinned by a dist-key = literal
    conjunct to the same node and replicated tables fill the rest.  Any
    subquery/sublink disables FQS here (the reference walks deeper;
    pgxcship.c handles many more cases — future widening).
    """
    if not isinstance(bq, BoundQuery):
        return None   # set operations: no single-node shipping yet
    loc = Locator(catalog)
    target: Optional[int] = None
    if _has_sublinks(bq):
        return None
    for rte in bq.rtable:
        if rte.kind != "table":
            return None
        dt = rte.table.distribution.dist_type
        if dt == DistType.REPLICATED:
            continue
        if dt not in (DistType.SHARD, DistType.HASH, DistType.MODULO):
            return None
        pins = dist_key_pins(rte, bq.where)
        if pins is None:
            return None
        node = loc.node_for_values(rte.table, pins)
        if node is None:
            return None
        if target is None:
            target = node
        elif target != node:
            return None
    return target


def fqs_param_router(bq: BoundQuery, catalog: Catalog):
    """FQS for PREPAREd statements: like fqs_target_node, but dist keys
    may be pinned by `= $n` parameters whose values arrive at EXECUTE.
    Returns a route(params: {name: (value, type)}) -> Optional[int]
    closure, or None when the statement can never ship whole (reference:
    the light-coordinator single-node resolution, execLight.c:34-59).
    """
    if not isinstance(bq, BoundQuery):
        return None
    loc = Locator(catalog)
    if _has_sublinks(bq):
        return None
    # per sharded table: the pin expr (Lit or __bindparam name) per col
    pinned: list[tuple] = []   # (TableDef, [E.Lit | param name])
    for rte in bq.rtable:
        if rte.kind != "table":
            return None
        dt = rte.table.distribution.dist_type
        if dt == DistType.REPLICATED:
            continue
        if dt not in (DistType.SHARD, DistType.HASH, DistType.MODULO):
            return None
        pins = dist_key_pins(rte, bq.where, allow_params=True)
        if pins is None:
            return None
        pinned.append((rte.table, pins))

    def route(params: dict):
        target = None
        for td, specs in pinned:
            vals = []
            for s in specs:
                if isinstance(s, str):
                    if s not in params:
                        return None
                    v, vt = params[s]
                    # wrap as a typed literal so point routing applies
                    # the literal-scale canonicalization (a raw scaled
                    # DECIMAL int would be re-scaled -> wrong node)
                    vals.append(E.Lit(v, vt))
                else:
                    vals.append(s)
            node = loc.node_for_values(td, vals)
            if node is None or (target is not None and node != target):
                return None
            if target is None:
                target = node
        return target

    return route


# ---------------------------------------------------------------------------
# distribution annotation + exchange insertion
# ---------------------------------------------------------------------------

class Distributor:
    def __init__(self, catalog: Catalog, n_datanodes: int):
        self.catalog = catalog
        self.ndn = n_datanodes
        self.exchanges: list[Exchange] = []
        self.fragments: list[Fragment] = []

    # -- main entry --
    def distribute(self, planned: PlannedStmt,
                   bq: BoundQuery) -> DistPlan:
        fqs = fqs_target_node(bq, self.catalog) if bq is not None else None
        if fqs is not None:
            frag = Fragment(0, planned.plan, "dn")
            return DistPlan([frag], [], 0, planned.init_plans,
                            planned.output_names, fqs_node=fqs)

        # distribute init plans too (each becomes its own DistPlan run by
        # the executor before the main plan)
        plan, dist = self._walk(planned.plan)
        if dist.kind != "cn":
            plan = self._add_gather(plan, one=(dist.kind == "replicated"))
        top = self._fragmentize(plan, "cn")
        return DistPlan(self.fragments, self.exchanges, top,
                        planned.init_plans, planned.output_names)

    # -- annotation walk: returns (new_plan, Dist) --
    def _walk(self, node: P.PhysNode):
        if isinstance(node, (P.SeqScan, P.IndexScan)):
            dt = node.table.distribution
            if dt.dist_type == DistType.REPLICATED:
                return node, Dist("replicated")
            keys = tuple(f"{node.alias}.{c}" for c in dt.dist_cols) \
                if dt.dist_type == DistType.SHARD else ()
            return node, Dist("sharded", keys, dt.group)

        if isinstance(node, P.AnnSearch):
            dt = node.table.distribution
            if dt.dist_type == DistType.REPLICATED:
                return node, Dist("replicated")
            # per-DN top-k, merge by distance at CN (pgvector on XC does
            # exactly this shape: DN IVFFlat scans under a CN merge)
            from ..catalog import types as T
            gathered = self._add_gather(node)
            cn_sort = P.Sort(gathered,
                             [(E.Col(node.dist_name, T.FLOAT64), False)],
                             node.k)
            return cn_sort, Dist("cn")

        if isinstance(node, P.Filter):
            node.child, d = self._walk(node.child)
            return node, d

        if isinstance(node, P.Project):
            node.child, d = self._walk(node.child)
            # track dist keys through renames
            if d.kind == "sharded" and d.keys:
                out = []
                for k in d.keys:
                    hit = [n for n, e in node.outputs
                           if isinstance(e, E.Col) and e.name == k]
                    if not hit:
                        return node, Dist("sharded", ())
                    out.append(hit[0])
                return node, Dist("sharded", tuple(out))
            return node, d

        if isinstance(node, P.Window):
            node.child, d = self._walk(node.child)
            if d.kind != "sharded":
                return node, d
            # local only when every call partitions by (at least) the
            # distribution keys — partitions then never span nodes
            # (reference: window paths keep Distribution when partition
            # clause covers the distribution key)
            common = None
            for _, wc in node.calls:
                this = {k.name for k in wc.partition
                        if isinstance(k, E.Col)}
                common = this if common is None else (common & this)
            if d.keys and common and set(d.keys) <= common:
                return node, d
            node.child = self._add_gather(node.child)
            return node, Dist("cn")

        if isinstance(node, P.HashJoin):
            return self._walk_join(node)

        if isinstance(node, P.Agg):
            return self._walk_agg(node)

        if isinstance(node, P.Sort):
            node.child, d = self._walk(node.child)
            if d.kind == "sharded":
                # per-DN top-k, merge at CN, re-limit there.  With a
                # limit the DN side sorts AND cuts to limit(+offset)
                # first, so the gather ships ndn*limit rows instead of
                # every group (reference: SimpleSort on RemoteSubplan,
                # planner.h:38-47 — the DN pre-sorts, the combiner
                # merges; the top-k union provably contains the global
                # top-k under the same total order)
                gathered = self._add_gather(node.child,
                                            sort_keys=node.keys,
                                            limit=node.limit)
                cn_sort = P.Sort(gathered, node.keys, node.limit)
                return cn_sort, Dist("cn")
            return node, d

        if isinstance(node, P.Limit):
            node.child, d = self._walk(node.child)
            if d.kind == "sharded":
                node.child = self._add_gather(node.child)
                d = Dist("cn")
            return node, d

        if isinstance(node, P.Append):
            # UNION ALL / partition-parent expansion: when every branch
            # is sharded the append runs PER-SHARD on the datanodes
            # (partitioned by unknown key — downstream joins/aggs add
            # their own redistribution), which keeps the device data
            # plane for union-fed joins.  All-replicated appends stay
            # replicated.  Mixed shapes gather to the CN (correct
            # everywhere, slower).
            walked = [self._walk(c) for c in node.inputs]
            kinds = {cd.kind for _cp, cd in walked}
            if kinds == {"sharded"}:
                node.inputs = [cp for cp, _cd in walked]
                return node, Dist("sharded", ())
            if kinds == {"replicated"}:
                node.inputs = [cp for cp, _cd in walked]
                return node, Dist("replicated")
            new_inputs = []
            for cp, cd in walked:
                if cd.kind != "cn":
                    cp = self._add_gather(cp,
                                          one=(cd.kind == "replicated"))
                new_inputs.append(cp)
            node.inputs = new_inputs
            return node, Dist("cn")

        if isinstance(node, P.SetOp):
            # INTERSECT/EXCEPT dedupe semantics: combine at the CN
            new_inputs = []
            for c in node.inputs:
                cp, cd = self._walk(c)
                if cd.kind != "cn":
                    cp = self._add_gather(cp,
                                          one=(cd.kind == "replicated"))
                new_inputs.append(cp)
            node.inputs = new_inputs
            return node, Dist("cn")

        if isinstance(node, P.Result):
            return node, Dist("cn")

        raise ValueError(f"cannot distribute {type(node).__name__}")

    # -- joins --
    def _join_pairs(self, node: P.HashJoin):
        return list(zip(node.left_keys, node.right_keys))

    def _walk_join(self, node: P.HashJoin):
        node.left, ld = self._walk(node.left)
        node.right, rd = self._walk(node.right)
        # one datanode: every placement is trivially colocated — skip
        # exchanges entirely (reference: single-node plans carry no
        # RemoteSubplan; also the single-chip TPU bench shape)
        if self.ndn == 1 and ld.kind in ("sharded", "replicated") \
                and rd.kind in ("sharded", "replicated"):
            return node, (ld if ld.kind == "sharded" else rd)
        pairs = self._join_pairs(node)

        def sharded_on_join_key(d: Dist, side: int):
            """Ordered join-pair indexes covering ALL of d.keys, or
            None.  Multi-column distribution keys align only when every
            key column appears as a join key, in distribution-key order
            (the hash is order-sensitive)."""
            if d.kind != "sharded" or not d.keys:
                return None
            idxs = []
            for key in d.keys:
                hit = None
                for i, pr in enumerate(pairs):
                    k = pr[side]
                    if isinstance(k, E.Col) and k.name == key:
                        hit = i
                        break
                if hit is None:
                    return None
                idxs.append(hit)
            return tuple(idxs)

        li = sharded_on_join_key(ld, 0)
        ri = sharded_on_join_key(rd, 1)

        if node.kind == "cross":
            if rd.kind != "replicated":
                node.right = self._add_broadcast(node.right)
            return node, (ld if ld.kind != "replicated"
                          else Dist("replicated"))

        if node.kind == "full":
            # FULL JOIN emits unmatched rows from BOTH sides: broadcast
            # would duplicate them per node.  Colocated/replicated pairs
            # stay local; otherwise join at the coordinator.
            if (li is not None and ri is not None and li == ri) or \
                    (ld.kind == "replicated" and rd.kind == "replicated"):
                return node, (ld if ld.kind != "replicated" else rd)
            if ld.kind != "cn":
                node.left = self._add_gather(
                    node.left, one=(ld.kind == "replicated"))
            if rd.kind != "cn":
                node.right = self._add_gather(
                    node.right, one=(rd.kind == "replicated"))
            return node, Dist("cn")

        # colocated: both sharded on the same join pairs (same order)
        # within the SAME node group's shard map
        if li is not None and ri is not None and li == ri \
                and ld.group == rd.group:
            return node, ld
        if ld.kind == "replicated" and rd.kind == "replicated":
            return node, Dist("replicated")
        if rd.kind == "replicated" and ld.kind == "sharded":
            return node, ld
        if ld.kind == "replicated" and rd.kind == "sharded":
            if node.kind == "inner":
                return node, rd
            # left/semi/anti with replicated probe side: broadcast build
            node.right = self._add_broadcast(node.right)
            return node, ld

        # need movement.  Prefer keeping the already-aligned side —
        # only when its placement rides the DEFAULT shard map, which is
        # what exchanges route by (a group table's alignment cannot be
        # matched by a default-map redistribute)
        if li is not None and ld.group == "default_group":
            node.right = self._add_redistribute(
                node.right, [pairs[i][1] for i in li])
            return node, ld
        if ri is not None and rd.group == "default_group":
            node.left = self._add_redistribute(
                node.left, [pairs[i][0] for i in ri])
            return node, rd
        if not pairs:
            # no equi keys (pure residual join): broadcast build side
            node.right = self._add_broadcast(node.right)
            return node, ld
        # cost choice (reference: create_remotesubplan_path weighing
        # replication vs redistribution): a SMALL build side broadcasts
        # once instead of moving both sides — needs ANALYZE estimates
        if node.kind == "inner":
            rest = _subtree_est(node.right)
            lest = _subtree_est(node.left)
            if rest is not None and rest <= 4096 and \
                    (lest is None or lest > 8 * rest):
                node.right = self._add_broadcast(node.right)
                return node, ld
        # redistribute both by the full key set
        node.left = self._add_redistribute(node.left,
                                           [p[0] for p in pairs])
        node.right = self._add_redistribute(node.right,
                                            [p[1] for p in pairs])
        lk = pairs[0][0]
        return node, Dist("sharded",
                          (lk.name,) if isinstance(lk, E.Col) and
                          len(pairs) == 1 else ())

    # -- aggregation --
    def _walk_agg(self, node: P.Agg):
        node.child, d = self._walk(node.child)
        if d.kind in ("replicated", "cn"):
            return node, d
        if self.ndn == 1:
            return node, d      # one DN: groups are whole already
        key_names = set()
        for _, ke in node.group_keys:
            if isinstance(ke, E.Col):
                key_names.add(ke.name)
        if d.kind == "sharded" and d.keys and set(d.keys) <= key_names:
            return node, d          # groups are node-local

        distinct = any(ac.distinct for _, ac in node.aggs)
        if node.group_keys and not distinct:
            # partial per DN -> redistribute by group keys -> final
            partial = P.Agg(node.child, node.group_keys, node.aggs,
                            "partial")
            red = self._add_redistribute(
                partial, [E.Col(n, ke.type)
                          for (n, ke) in node.group_keys])
            final = P.Agg(red, [(n, E.Col(n, ke.type))
                                for (n, ke) in node.group_keys],
                          node.aggs, "final")
            return final, Dist("sharded",
                               (node.group_keys[0][0],)
                               if len(node.group_keys) == 1 else ())
        if node.group_keys:
            # distinct aggs: move whole groups to their owner node first
            red = self._add_redistribute(
                node.child, [ke for (_, ke) in node.group_keys])
            node.child = red
            return node, Dist("sharded", ())
        if distinct:
            # global count(DISTINCT): per-DN distinct counts cannot be
            # summed (values straddle nodes) — gather the rows, dedupe at CN
            node.child = self._add_gather(node.child)
            return node, Dist("cn")
        # global aggregate: partial per DN -> gather -> final at CN
        partial = P.Agg(node.child, [], node.aggs, "partial")
        gathered = self._add_gather(partial)
        final = P.Agg(gathered, [], node.aggs, "final")
        return final, Dist("cn")

    # -- exchange insertion --
    def _add_redistribute(self, child: P.PhysNode,
                          keys: list[E.Expr]) -> P.PhysNode:
        return P.Redistribute(child, keys)

    def _add_broadcast(self, child: P.PhysNode) -> P.PhysNode:
        return P.Broadcast(child)

    def _add_gather(self, child: P.PhysNode, sort_keys=None,
                    one: bool = False, limit=None) -> P.PhysNode:
        return P.Gather(child, sort_keys or [], one, limit)

    # -- fragmentation at exchange boundaries --
    def _fragmentize(self, plan: P.PhysNode, location: str) -> int:
        """Cut at exchange nodes; returns the index of the fragment whose
        plan is `plan` with exchange children replaced by ExchangeRef."""

        def cut(node: P.PhysNode) -> P.PhysNode:
            if isinstance(node, (P.Redistribute, P.Broadcast, P.Gather)):
                child_loc = "dn"
                src = self._fragmentize(node.child, child_loc)
                kind = {"Redistribute": "redistribute",
                        "Broadcast": "broadcast",
                        "Gather": "gather"}[type(node).__name__]
                if kind == "gather" and getattr(node, "one", False):
                    kind = "gather_one"
                ex = Exchange(len(self.exchanges), kind,
                              getattr(node, "keys", []), src,
                              sort_keys=getattr(node, "sort_keys", []),
                              limit=getattr(node, "limit", None))
                self.exchanges.append(ex)
                return ExchangeRef(ex.index)
            for attr in ("child", "left", "right"):
                c = getattr(node, attr, None)
                if isinstance(c, P.PhysNode):
                    setattr(node, attr, cut(c))
            if isinstance(node, (P.Append, P.SetOp)):
                node.inputs = [cut(c) for c in node.inputs]
            return node

        body = cut(plan)
        frag = Fragment(len(self.fragments), body, location)
        self.fragments.append(frag)
        return frag.index
