"""Planner: BoundQuery -> physical plan.

Reference analog: src/backend/optimizer (standard_planner path) plus the XC
distributed planning in src/backend/pgxc/plan/planner.c and
optimizer/util/pgxcship.c.  This module covers the single-fragment (local)
plan shape; distribution decisions (FQS vs fragments with exchanges) are
layered on in plan/distribute.py.

Subquery strategy (the reference's v2.2 headline feature was exactly this
rewrite family — "subquery -> correlated query rewrite + DN pushdown"):
- EXISTS / IN (subquery)           -> semi / anti HashJoin
- uncorrelated scalar subquery     -> init plan (executed once, substituted)
- correlated scalar aggregate      -> decorrelation: grouped derived table
                                      joined on the correlation keys
Join order: greedy connection-aware ordering over the equi-join conjunct
graph (no cross joins unless forced), left-deep, new table as build side.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

import numpy as np

from ..catalog.catalog import Catalog
from ..catalog import types as T
from ..catalog.types import TypeKind
from . import exprs as E
from . import physical as P
from .query import BoundQuery, JoinStep, RTE, SubLink


class PlanError(Exception):
    pass


@dataclasses.dataclass
class InitPlan:
    name: str
    plan: P.PhysNode
    type: T.SqlType


@dataclasses.dataclass
class PlannedStmt:
    plan: P.PhysNode
    init_plans: list[InitPlan]
    output_names: list[str]
    # join order the planner chose for the main query (alias sequence)
    # — what an SPM baseline captures (optimizer/spm/spm.c semantics)
    join_order_chosen: list = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# expression utilities
# ---------------------------------------------------------------------------

def expr_cols(e: E.Expr) -> set[str]:
    out = set()
    for x in E.walk(e):
        if isinstance(x, E.Col):
            out.add(x.name)
    return out


def rewrite(e: E.Expr, fn) -> E.Expr:
    """Bottom-up rewrite; fn(node) returns replacement or None."""
    def rec(x: E.Expr) -> E.Expr:
        r = fn(x)
        if r is not None:
            return r
        if isinstance(x, E.Arith):
            return E.Arith(x.op, rec(x.left), rec(x.right))
        if isinstance(x, E.Neg):
            return E.Neg(rec(x.arg))
        if isinstance(x, E.Cmp):
            return E.Cmp(x.op, rec(x.left), rec(x.right))
        if isinstance(x, E.BoolOp):
            return E.BoolOp(x.op, tuple(rec(a) for a in x.args))
        if isinstance(x, E.Not):
            return E.Not(rec(x.arg))
        if isinstance(x, E.Case):
            return E.Case(tuple((rec(c), rec(v)) for c, v in x.whens),
                          rec(x.else_) if x.else_ is not None else None,
                          x.case_type)
        if isinstance(x, E.InList):
            return E.InList(rec(x.arg), x.values)
        if isinstance(x, E.Extract):
            return E.Extract(x.field, rec(x.arg))
        if isinstance(x, E.Cast):
            return E.Cast(rec(x.arg), x.to)
        if isinstance(x, E.AggCall):
            return E.AggCall(x.func, rec(x.arg) if x.arg is not None
                             else None, x.distinct)
        if isinstance(x, E.WindowCall):
            return E.WindowCall(
                x.func, rec(x.arg) if x.arg is not None else None,
                tuple(rec(p) for p in x.partition),
                tuple((rec(o), d) for o, d in x.order),
                x.offset,
                rec(x.default) if x.default is not None else None,
                x.frame)
        if isinstance(x, E.Coalesce):
            return E.Coalesce(tuple(rec(a) for a in x.args), x.out_type)
        if isinstance(x, E.NullIf):
            return E.NullIf(rec(x.left), rec(x.right))
        if isinstance(x, E.IsNull):
            return E.IsNull(rec(x.arg), x.negated)
        return x
    return rec(e)


def _hoist_or_common(q: E.Expr) -> list[E.Expr]:
    """(a AND x AND ...) OR (a AND y AND ...) -> [a, (x... OR y...)]."""
    if not (isinstance(q, E.BoolOp) and q.op == "or" and len(q.args) > 1):
        return [q]
    from ..sql.analyze import split_conjuncts
    branch_sets = [split_conjuncts(a) for a in q.args]
    common = [c for c in branch_sets[0]
              if all(any(c == d for d in bs) for bs in branch_sets[1:])]
    if not common:
        return [q]
    rest_branches = []
    for bs in branch_sets:
        rest = [d for d in bs if not any(d == c for c in common)]
        if not rest:
            return common  # one branch fully covered: OR is implied true
        rest_branches.append(rest[0] if len(rest) == 1
                             else E.BoolOp("and", tuple(rest)))
    return common + [E.BoolOp("or", tuple(rest_branches))]


def _strpred_plain(p: E.StrPred) -> str:
    c = p.col.col if isinstance(p.col, E.TextExpr) else p.col
    return c.name.split(".", 1)[-1]


def _is_equi_pair(e: E.Expr):
    """conjunct of form Col = Col -> (left_col, right_col) exprs."""
    if isinstance(e, E.Cmp) and e.op == "=" \
            and isinstance(e.left, E.Col) and isinstance(e.right, E.Col):
        return e.left, e.right
    return None


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------

class Planner:
    def __init__(self, catalog: Catalog):
        self.catalog = catalog
        self._ip_counter = itertools.count()

    # -- public ------------------------------------------------------------
    def plan(self, bq, forced_order=None) -> PlannedStmt:
        from .query import BoundSetOp
        init_plans: list[InitPlan] = []
        if isinstance(bq, BoundSetOp):
            plan, names = self._plan_setop(bq, init_plans)
            return PlannedStmt(plan, init_plans, names)
        self._forced_order = list(forced_order) if forced_order else None
        self._order_chosen: list = []
        self._pq_calls = 0
        plan = self._plan_query(bq, init_plans)
        # a baseline is only trustworthy for single-query statements:
        # subqueries plan through the same walk and would interleave
        # their join order into the capture (and could wrongly consume
        # a forced order meant for the main query)
        chosen = self._order_chosen if self._pq_calls == 1 else []
        return PlannedStmt(plan, init_plans,
                           [n for n, _ in bq.targets],
                           join_order_chosen=chosen)

    def _plan_setop(self, so, init_plans):
        from .query import BoundSetOp

        def child_plan(c):
            if isinstance(c, BoundSetOp):
                p, names_, = self._plan_setop(c, init_plans)
                return p, names_, c.target_types
            p = self._plan_query(c, init_plans)
            return p, [n for n, _ in c.targets], [e.type for _, e
                                                  in c.targets]

        names = so.target_names
        inputs = []
        for child in (so.left, so.right):
            p, cnames, ctypes = child_plan(child)
            # positional rename onto the combined output names, coercing
            # decimal scales so appended values share a representation
            outs = []
            for i in range(len(names)):
                e = E.Col(cnames[i], ctypes[i])
                t = so.target_types[i]
                if ctypes[i].kind == TypeKind.NULL \
                        and t.kind != TypeKind.NULL:
                    # an all-NULL branch column (grouping-sets expansion)
                    # takes the combined type so TEXT decode/dict merge
                    # and numeric widths line up across branches
                    e = E.Lit(None, t)
                elif t.kind == ctypes[i].kind and \
                        t.scale != ctypes[i].scale:
                    e = E.Cast(e, t)
                outs.append((names[i], e))
            inputs.append(P.Project(p, outs))
        if so.op in ("intersect", "except"):
            plan = P.SetOp(inputs=inputs, op=so.op, all=so.all,
                           names=list(names),
                           types=list(so.target_types))
        else:
            plan = P.Append(inputs=inputs)
            if not so.all:
                plan = P.Agg(plan, [(n, E.Col(n, t)) for n, t in
                                    zip(names, so.target_types)], [],
                             "single")
        if so.order_by:
            keys = [(E.Col(names[i], so.target_types[i]), desc)
                    for i, desc in so.order_by]
            plan = P.Sort(plan, keys,
                          (so.limit + so.offset)
                          if so.limit is not None else None)
        if so.limit is not None or so.offset:
            plan = P.Limit(plan, so.limit, so.offset)
        return plan, names

    # -- query planning ----------------------------------------------------
    def _plan_query(self, bq: BoundQuery,
                    init_plans: list[InitPlan]) -> P.PhysNode:
        self._pq_calls = getattr(self, "_pq_calls", 0) + 1
        bq = self._rewrite_sublinks(bq, init_plans)

        # classify conjuncts
        rte_cols = {}
        for rte in bq.rtable:
            rte_cols[rte.alias] = {q for q, _ in rte.columns.values()}
        semijoins = getattr(bq, "_semijoins", [])

        scan_filters: dict[str, list[E.Expr]] = {r.alias: [] for r in bq.rtable}
        join_edges: list[tuple[str, str, E.Expr, E.Expr]] = []
        residual: list[E.Expr] = []

        def owner_of(cols: set[str]) -> Optional[str]:
            owners = {a for a, cs in rte_cols.items() if cols & cs}
            if len(owners) == 1:
                return owners.pop()
            return None

        # factor conjuncts common to every OR branch (TPC-H Q19: the join
        # key equality lives inside each bracket) — the reference optimizer
        # does the same via extract_restriction_or_clauses
        where = []
        for q in bq.where:
            where.extend(_hoist_or_common(q))

        all_cols = set()
        for cs_ in rte_cols.values():
            all_cols |= cs_
        param_filters = []   # reference no table column (init-plan probes)

        # WHERE quals touching the NULL-EXTENDED side of an outer join
        # must filter the JOIN OUTPUT: pushed into the scan they would
        # run before null-extension (a row failing them comes back as a
        # null-extended row), and as join residual they would get ON
        # semantics.  (Reference: reduce_outer_joins/qual placement in
        # initsplan.c — PG pushes only after proving strictness and
        # converting the join to inner; we keep the join and filter
        # above, which is always correct.)
        nullable_side: set[str] = set()
        for st_ in bq.join_order:
            if st_.kind == "left":
                nullable_side.add(bq.rtable[st_.rte_index].alias)
            elif st_.kind == "full":
                nullable_side = set(rte_cols)
                break
        nullable_cols = set()
        for a in nullable_side:
            nullable_cols |= rte_cols[a]
        post_filters: list[E.Expr] = []

        for q in where:
            cols = expr_cols(q)
            if not (cols & all_cols):
                param_filters.append(q)
                continue
            if cols & nullable_cols:
                post_filters.append(q)
                continue
            own = owner_of(cols)
            if own is not None:
                scan_filters[own].append(q)
                continue
            pair = _is_equi_pair(q)
            if pair is not None:
                lo = owner_of({pair[0].name})
                ro = owner_of({pair[1].name})
                if lo and ro and lo != ro:
                    join_edges.append((lo, ro, pair[0], pair[1]))
                    continue
            residual.append(q)

        # build scans
        scans: dict[str, P.PhysNode] = {}
        for rte in bq.rtable:
            scans[rte.alias] = self._plan_rte(rte, scan_filters[rte.alias],
                                              init_plans)

        plan, avail = self._join_tables(bq, scans, rte_cols, join_edges,
                                        residual, semijoins, init_plans)

        # leftover residual quals
        still = [q for q in residual if not expr_cols(q) <= avail]
        if still:
            raise PlanError(f"unplaceable predicates: {still}")
        if post_filters:
            missing = [q for q in post_filters
                       if not expr_cols(q) <= avail]
            if missing:
                raise PlanError(f"unplaceable predicates: {missing}")
            plan = P.Filter(plan, post_filters)
        if param_filters:
            plan = P.Filter(plan, param_filters)

        # aggregation / projection
        plan, out_names = self._plan_agg_project(bq, plan)
        return plan

    # -- RTE scan ----------------------------------------------------------
    def _plan_rte(self, rte: RTE, filters, init_plans) -> P.PhysNode:
        if rte.kind == "table":
            # scan emits qualified names
            outputs = [(q, E.Col(q, t)) for _, (q, t) in rte.columns.items()]
            scan = self._try_index_scan(rte, filters, outputs)
            if scan is None:
                scan = P.SeqScan(rte.table, rte.alias, filters, outputs)
            # estimate rides on the node for the distributed planner's
            # broadcast-vs-redistribute choice
            scan.est_rows = self._est_scan(rte, filters)
            return scan
        from .query import BoundSetOp
        if isinstance(rte.subquery, BoundSetOp):
            sub, _names = self._plan_setop(rte.subquery, init_plans)
        else:
            sub = self._plan_query(rte.subquery, init_plans)
        return _RenameHelper.wrap(sub, rte, filters)

    def _try_index_scan(self, rte: RTE, filters,
                        outputs) -> Optional[P.PhysNode]:
        """Rewrite a scan into an IndexScan when a filter bounds an
        indexed column (reference: create_index_paths +
        ExecIndexBuildScanKeys).  Bounds are converted into the storage
        representation; the filter list stays intact and re-verifies."""
        indexed = self.catalog.btree_cols.get(rte.table.name) or set()
        if not indexed:
            return None
        best = None
        for q in filters:
            if not (isinstance(q, E.Cmp) and isinstance(q.left, E.Col)
                    and isinstance(q.right, E.Lit)
                    and q.right.value is not None):
                continue
            plain = q.left.name.split(".", 1)[-1]
            if plain not in indexed:
                continue
            col = rte.table.column(plain)
            if col.type.kind == TypeKind.TEXT:
                continue   # codes are unordered; text btree is a follow-up
            v = self._storage_bound(col.type, q.right)
            if v is None:
                continue
            b = best
            if b is None:
                b = {"col": plain, "lo": None, "hi": None,
                     "lo_strict": False, "hi_strict": False}
            elif b["col"] != plain:
                continue    # one index per scan for now
            op = q.op
            if op == "=":
                b["lo"] = v if b["lo"] is None else max(b["lo"], v)
                b["hi"] = v if b["hi"] is None else min(b["hi"], v)
            elif op in (">", ">="):
                if b["lo"] is None or v >= b["lo"]:
                    b["lo"], b["lo_strict"] = v, (op == ">")
            elif op in ("<", "<="):
                if b["hi"] is None or v <= b["hi"]:
                    b["hi"], b["hi_strict"] = v, (op == "<")
            else:
                continue
            best = b
        if best is None or (best["lo"] is None and best["hi"] is None):
            return None
        return P.IndexScan(rte.table, rte.alias, best["col"],
                           best["lo"], best["hi"], best["lo_strict"],
                           best["hi_strict"], filters, outputs)

    @staticmethod
    def _storage_bound(ct, lit: E.Lit):
        """Literal -> the column's storage representation for index
        comparison; None when not convertible."""
        from ..catalog import types as T
        v, lt = lit.value, lit.lit_type
        k = ct.kind
        try:
            if k == TypeKind.DECIMAL:
                if lt.kind == TypeKind.DECIMAL:
                    diff = ct.scale - lt.scale
                    return int(v) * 10 ** diff if diff >= 0 else \
                        int(v) / 10 ** (-diff)
                if isinstance(v, (int, np.integer)):
                    return int(v) * 10 ** ct.scale
                return T.decimal_to_int(str(v), ct.scale)
            if k == TypeKind.DATE:
                return T.date_to_days(v) if isinstance(v, str) else int(v)
            if k == TypeKind.FLOAT64:
                if lt.kind == TypeKind.DECIMAL:
                    return int(v) / 10 ** lt.scale
                return float(v)
            if k in (TypeKind.INT32, TypeKind.INT64):
                if lt.kind == TypeKind.DECIMAL:
                    # fractional bound against an int column: keep the
                    # float (searchsorted handles mixed compare)
                    return int(v) / 10 ** lt.scale if lt.scale else int(v)
                return int(v)
        except (TypeError, ValueError):
            return None
        return None

    # -- statistics / cost estimation --------------------------------------
    DEFAULT_ROWS = 1000.0

    def _table_stats(self, rte: RTE) -> Optional[dict]:
        if rte.kind != "table":
            return None
        return self.catalog.stats.get(rte.table.name)

    def _est_scan(self, rte: RTE, filters) -> Optional[float]:
        """Estimated scan output rows, or None without ANALYZE stats
        (reference: costsize.c set_baserel_size_estimates +
        clause_selectivity)."""
        st = self._table_stats(rte)
        if st is None:
            return None
        rows = float(max(st["rows"], 1))
        for q in filters:
            sel = 0.33
            if isinstance(q, E.Cmp) and isinstance(q.left, E.Col) \
                    and isinstance(q.right, E.Lit):
                plain = q.left.name.split(".", 1)[-1]
                cst = st["cols"].get(plain)
                if q.op == "=":
                    sel = 1.0 / max(cst["ndv"], 1) if cst else 0.1
                elif cst and cst.get("min") is not None and \
                        q.op in ("<", "<=", ">", ">="):
                    v = self._storage_bound(
                        rte.table.column(plain).type, q.right)
                    if v is not None:
                        hist = cst.get("hist")
                        if hist:
                            # equi-depth quantile interpolation: each
                            # bucket holds 1/(len-1) of the rows, so
                            # the bound's insertion position IS the
                            # cumulative fraction (skew-robust;
                            # reference: ineq_histogram_selectivity)
                            import numpy as _np
                            frac = float(
                                _np.searchsorted(_np.asarray(hist),
                                                 float(v))
                                / (len(hist) - 1))
                        else:
                            span = max(cst["max"] - cst["min"], 1e-9)
                            frac = (float(v) - cst["min"]) / span
                        frac = min(max(frac, 0.0), 1.0)
                        sel = frac if q.op in ("<", "<=") else 1.0 - frac
            elif isinstance(q, E.StrPred):
                cst = st["cols"].get(_strpred_plain(q))
                if q.kind in ("eq", "in"):
                    k = len(q.patterns)
                    sel = k / max(cst["ndv"], 1) if cst else 0.1
                elif q.kind in ("like",):
                    sel = 0.1
                else:
                    sel = 0.33
            elif isinstance(q, E.InList):
                sel = 0.2
            rows *= max(sel, 1e-6)
        return max(rows, 1.0)

    def _edge_ndv(self, expr: E.Expr, alias_rtes: dict) -> float:
        if isinstance(expr, E.Col) and "." in expr.name:
            alias, plain = expr.name.split(".", 1)
            rte = alias_rtes.get(alias)
            st = self._table_stats(rte) if rte is not None else None
            if st and plain in st["cols"]:
                return float(max(st["cols"][plain]["ndv"], 1))
        return 0.0

    # -- join ordering -----------------------------------------------------
    def _join_tables(self, bq, scans, rte_cols, join_edges, residual,
                     semijoins, init_plans):
        order = [s.rte_index for s in bq.join_order]
        aliases = [bq.rtable[i].alias for i in order]
        outer_steps = {bq.rtable[s.rte_index].alias: s
                       for s in bq.join_order if s.kind in ("left",
                                                            "full")}
        alias_rtes = {bq.rtable[i].alias: bq.rtable[i] for i in order}

        joined: list[str] = []
        plan: Optional[P.PhysNode] = None
        avail: set[str] = set()
        remaining = list(aliases)

        def edges_between(cand: str):
            out = []
            for lo, ro, le, re_ in join_edges:
                if ro == cand and lo in joined:
                    out.append((le, re_))
                elif lo == cand and ro in joined:
                    out.append((re_, le))
            return out

        # cost mode needs every base table ANALYZEd (reference:
        # costsize.c falls back to defaults; we fall back to the greedy
        # FROM-order walk, the round-1 behavior)
        base_est = {a: self._est_scan(alias_rtes[a],
                                      getattr(scans[a], "filters", []))
                    for a in aliases}
        cost_mode = all(v is not None for v in base_est.values()) \
            and len(aliases) > 1
        cur_est = 0.0

        def join_est(cand: str) -> float:
            edges = edges_between(cand)
            if not edges:
                return cur_est * base_est[cand]  # cross
            sel = 1.0
            for le, re_ in edges:
                ndv = max(self._edge_ndv(le, alias_rtes),
                          self._edge_ndv(re_, alias_rtes))
                if ndv <= 0:
                    ndv = max(cur_est, base_est[cand], 1.0)
                sel *= 1.0 / ndv
            return max(cur_est * base_est[cand] * sel, 1.0)

        forced = list(getattr(self, "_forced_order", None) or [])
        if forced and (set(forced) != set(aliases) or outer_steps
                       or semijoins):
            forced = []          # stale/ineligible baseline: ignore
        while remaining:
            cand = None
            if forced:
                cand = forced[len(joined)]
            # outer joins are not reorderable past inner candidates:
            # take the next FROM-order outer step as soon as it appears
            elif remaining[0] in outer_steps and plan is not None:
                cand = remaining[0]
            elif cost_mode and plan is None:
                # starting table = one side of the cheapest join pair
                # (Selinger's level-2 seed, costsize.c-style)
                best_cost = None
                for lo_a, ro_a, le, re_ in join_edges:
                    if lo_a in outer_steps or ro_a in outer_steps:
                        continue
                    ndv = max(self._edge_ndv(le, alias_rtes),
                              self._edge_ndv(re_, alias_rtes)) or \
                        max(base_est[lo_a], base_est[ro_a], 1.0)
                    c = base_est[lo_a] * base_est[ro_a] / ndv
                    if best_cost is None or c < best_cost:
                        best_cost = c
                        cand = lo_a if base_est[lo_a] >= base_est[ro_a] \
                            else ro_a
            elif cost_mode and plan is not None:
                best_cost = None
                for a in remaining:
                    if a in outer_steps:
                        continue
                    if not edges_between(a) and len(remaining) > 1:
                        continue   # delay cross joins
                    c = join_est(a)
                    if best_cost is None or c < best_cost:
                        best_cost, cand = c, a
            if cand is None:
                for a in remaining:
                    # an outer step may only fire in FROM order — its
                    # null-preserved left side must already be joined
                    if plan is None or edges_between(a) \
                            or (a in outer_steps and a == remaining[0]):
                        cand = a
                        break
            if cand is None:
                cand = remaining[0]      # forced cross join
            remaining.remove(cand)
            joined_order = getattr(self, "_order_chosen", None)
            if joined_order is not None:
                joined_order.append(cand)
            if cost_mode:
                cur_est = base_est[cand] if plan is None \
                    else join_est(cand)
            right = scans[cand]
            if plan is None:
                plan = right
            else:
                step = outer_steps.get(cand)
                if step is not None:
                    lk, rk, res = self._outer_keys(step.on, avail,
                                                   rte_cols[cand])
                    if step.kind == "full" and res:
                        raise PlanError("FULL JOIN supports only "
                                        "equi-key ON conditions")
                    plan = P.HashJoin(plan, right, lk, rk, step.kind,
                                      res)
                else:
                    edges = edges_between(cand)
                    if edges:
                        lk = [le for le, _ in edges]
                        rk = [re_ for _, re_ in edges]
                        plan = P.HashJoin(plan, right, lk, rk, "inner", [])
                    else:
                        plan = P.HashJoin(plan, right, [], [], "cross", [])
            joined.append(cand)
            avail |= rte_cols[cand]
            # attach residual quals that just became evaluable
            now = [q for q in residual if expr_cols(q) <= avail]
            for q in now:
                residual.remove(q)
                plan = P.Filter(plan, [q])
            # attach semi/anti joins whose outer cols are now available
            for sj in list(semijoins):
                if sj["outer_cols"] <= avail:
                    semijoins.remove(sj)
                    plan = P.HashJoin(plan, sj["plan"], sj["outer_keys"],
                                      sj["inner_keys"], sj["kind"],
                                      sj["residual"])
        if plan is None:
            plan = P.Result(outputs=[])
        return plan, avail

    def _outer_keys(self, on: E.Expr, avail: set[str], right_cols: set[str]):
        from ..sql.analyze import split_conjuncts
        lk, rk, res = [], [], []
        for q in split_conjuncts(on):
            pair = _is_equi_pair(q)
            if pair is not None:
                a, b = pair
                if a.name in avail and b.name in right_cols:
                    lk.append(a)
                    rk.append(b)
                    continue
                if b.name in avail and a.name in right_cols:
                    lk.append(b)
                    rk.append(a)
                    continue
            res.append(q)
        if not lk:
            raise PlanError("outer join requires at least one equi-key")
        return lk, rk, res

    # -- sublink rewrites --------------------------------------------------
    def _rewrite_sublinks(self, bq: BoundQuery,
                          init_plans: list[InitPlan]) -> BoundQuery:
        semijoins = []
        new_where = []

        def scalar_replacement(sl: SubLink) -> E.Expr:
            if sl.query.correlated_cols:
                return self._decorrelate_scalar(sl, bq, init_plans)
            name = f"__initplan{next(self._ip_counter)}"
            sub = self._plan_query(sl.query, init_plans)
            t = sl.query.targets[0][1].type
            init_plans.append(InitPlan(name, sub, t))
            return E.Col(name, t)

        def rewrite_scalars(e: E.Expr) -> E.Expr:
            return rewrite(e, lambda x: scalar_replacement(x)
                           if isinstance(x, SubLink)
                           and x.link_kind == "scalar" else None)

        def uncorrelated_exists(sl: SubLink) -> E.Expr:
            """EXISTS with no outer reference: one-row init plan probing
            whether any row exists, folded to a boolean."""
            probe = dataclasses.replace(
                sl.query, targets=[("__one", E.Lit(1, T.INT64))],
                group_by=[], having=[], order_by=[], limit=1, offset=None)
            name = f"__initplan{next(self._ip_counter)}"
            init_plans.append(InitPlan(name, self._plan_query(probe,
                                                              init_plans),
                                       T.INT64))
            op = "<>" if sl.negated else "="
            return E.Cmp(op, E.Col(name, T.INT64), E.Lit(1, T.INT64))

        for q in bq.where:
            if isinstance(q, E.Not) and isinstance(q.arg, SubLink) \
                    and q.arg.link_kind in ("exists", "in"):
                q = SubLink(q.arg.link_kind, q.arg.query, q.arg.test_expr,
                            q.arg.cmp_op, not q.arg.negated)
            if isinstance(q, SubLink) and q.link_kind in ("exists", "in"):
                if q.link_kind == "exists" and not q.query.correlated_cols:
                    new_where.append(uncorrelated_exists(q))
                    continue
                sj = self._sublink_to_semijoin(q, init_plans)
                semijoins.append(sj)
                new_where.extend(sj.pop("extra_quals"))
                continue
            new_where.append(rewrite_scalars(q))

        bq = dataclasses.replace(bq, where=new_where)
        bq.targets = [(n, rewrite_scalars(e)) for n, e in bq.targets]
        bq.having = [rewrite_scalars(e) for e in bq.having]
        bq._semijoins = semijoins
        return bq

    def _sublink_to_semijoin(self, sl: SubLink, init_plans) -> dict:
        sub = sl.query
        kind = "anti" if sl.negated else "semi"
        outer_keys: list[E.Expr] = []
        inner_keys: list[E.Expr] = []
        residual: list[E.Expr] = []
        extra_quals: list[E.Expr] = []

        if sl.link_kind == "in":
            if sub.correlated_cols:
                raise PlanError("correlated IN subquery unsupported")
            if len(sub.targets) != 1:
                raise PlanError("IN subquery must return one column")
            tname, texpr = sub.targets[0]
            outer_keys.append(sl.test_expr)
            inner_keys.append(E.Col(f"__sub.{tname}", texpr.type))
            if kind == "anti":
                # SQL 3VL NOT IN: x NOT IN (S) is TRUE only when S is
                # empty, or x IS NOT NULL ∧ S has no NULL ∧ no match
                # (reference: the negated ANY sublink semantics of
                # ExecScanSubPlan / nodeSubplan.c — a NULL on either
                # side makes the result UNKNOWN, filtered like FALSE).
                # Two scalar init plans probe |S| and |S ∩ NULL|; the
                # anti join itself runs over the NULL-free inner rows so
                # canonicalized NULL keys can never hash-match.
                total = self._count_initplan(sub, tname, texpr.type,
                                             only_null=False,
                                             init_plans=init_plans)
                nnull = self._count_initplan(sub, tname, texpr.type,
                                             only_null=True,
                                             init_plans=init_plans)
                extra_quals.append(E.BoolOp("or", (
                    E.Cmp("=", E.Col(total, T.INT64), E.Lit(0, T.INT64)),
                    E.BoolOp("and", (
                        E.IsNull(sl.test_expr, negated=True),
                        E.Cmp("=", E.Col(nnull, T.INT64),
                              E.Lit(0, T.INT64)))))))
                sub = self._filter_null_keys(sub, tname, texpr.type)
            inner_plan = self._plan_query(sub, init_plans)
            inner_plan = _rename_outputs(inner_plan, sub, "__sub")
        else:  # exists
            corr = set(sub.correlated_cols)
            if not corr:
                raise PlanError("uncorrelated EXISTS unsupported (use limit)")
            inner_where = []
            for q in sub.where:
                pair = _is_equi_pair(q)
                if pair is not None:
                    a, b = pair
                    if a.name in corr and b.name not in corr:
                        outer_keys.append(a)
                        inner_keys.append(b)
                        continue
                    if b.name in corr and a.name not in corr:
                        outer_keys.append(b)
                        inner_keys.append(a)
                        continue
                cols = expr_cols(q)
                if cols & corr:
                    residual.append(q)   # evaluated over joined pairs
                    continue
                inner_where.append(q)
            if not outer_keys:
                raise PlanError("EXISTS without equality correlation "
                                "unsupported")
            sub2 = dataclasses.replace(sub, where=inner_where,
                                       targets=self._exists_targets(
                                           sub, inner_keys, residual))
            inner_plan = self._plan_query(sub2, init_plans)

        return {"kind": kind, "plan": inner_plan,
                "outer_keys": outer_keys, "inner_keys": inner_keys,
                "residual": residual, "extra_quals": extra_quals,
                "outer_cols": set().union(*(expr_cols(k)
                                            for k in outer_keys))}

    def _derived_rte(self, sub: BoundQuery, alias: str) -> RTE:
        return RTE(alias, "subquery", subquery=sub,
                   columns={n: (f"{alias}.{n}", e.type)
                            for n, e in sub.targets})

    def _count_initplan(self, sub: BoundQuery, key: str, key_t,
                        only_null: bool, init_plans) -> str:
        """Scalar init plan counting the IN-subquery's rows (optionally
        only its NULL keys), via a derived-table wrap so grouped
        subqueries count groups, not input rows."""
        import copy
        alias = f"__nin{next(self._ip_counter)}"
        rte = self._derived_rte(copy.deepcopy(sub), alias)
        where = [E.IsNull(E.Col(f"{alias}.{key}", key_t))] \
            if only_null else []
        probe = BoundQuery(rtable=[rte], join_order=[JoinStep(0, "inner")],
                           where=where,
                           targets=[("__c", E.AggCall("count", None))],
                           group_by=[], having=[], order_by=[])
        name = f"__initplan{next(self._ip_counter)}"
        init_plans.append(InitPlan(name, self._plan_query(probe,
                                                          init_plans),
                                   T.INT64))
        return name

    def _filter_null_keys(self, sub: BoundQuery, key: str,
                          key_t) -> BoundQuery:
        """NULL-free view of an IN subquery for the anti-join build side."""
        alias = f"__ninf{next(self._ip_counter)}"
        rte = self._derived_rte(sub, alias)
        return BoundQuery(
            rtable=[rte], join_order=[JoinStep(0, "inner")],
            where=[E.IsNull(E.Col(f"{alias}.{key}", key_t),
                            negated=True)],
            targets=[(key, E.Col(f"{alias}.{key}", key_t))],
            group_by=[], having=[], order_by=[])

    def _exists_targets(self, sub: BoundQuery, inner_keys, residual):
        """EXISTS subquery: project the join keys + any inner columns the
        residual quals need."""
        needed = {}
        for k in inner_keys:
            for c in expr_cols(k):
                needed[c] = k.type if isinstance(k, E.Col) else T.INT64
        for q in residual:
            for x in E.walk(q):
                if isinstance(x, E.Col):
                    needed.setdefault(x.name, x.col_type)
        corr = set(sub.correlated_cols)
        return [(qname, E.Col(qname, t)) for qname, t in needed.items()
                if qname not in corr]

    def _decorrelate_scalar(self, sl: SubLink, outer_bq: BoundQuery,
                            init_plans) -> E.Expr:
        """Correlated scalar aggregate -> grouped derived table + join.

        select ... where expr OP (select AGG(x) from T where T.k = outer.k
        and quals)  becomes  derived = select T.k, AGG(x) from T where quals
        group by T.k, joined on derived.k = outer.k; OP compares against
        the agg column.  (The reference implements this family of rewrites
        in its optimizer; v2.2 release note lines 3-4.)
        """
        sub = sl.query
        corr = set(sub.correlated_cols)
        inner_where, outer_keys, inner_keys = [], [], []
        for q in sub.where:
            pair = _is_equi_pair(q)
            if pair is not None:
                a, b = pair
                if a.name in corr and b.name not in corr:
                    outer_keys.append(a)
                    inner_keys.append(b)
                    continue
                if b.name in corr and a.name not in corr:
                    outer_keys.append(b)
                    inner_keys.append(a)
                    continue
            if expr_cols(q) & corr:
                raise PlanError("non-equality correlation in scalar "
                                "subquery unsupported")
            inner_where.append(q)
        if not outer_keys:
            raise PlanError("correlated scalar subquery without equality "
                            "correlation")
        val_name, val_expr = sub.targets[0]
        targets = [("__val", val_expr)] + \
            [(f"__k{i}", k) for i, k in enumerate(inner_keys)]
        derived = dataclasses.replace(
            sub, where=inner_where, targets=targets,
            group_by=list(inner_keys), having=[], order_by=[],
            limit=None, offset=None, correlated_cols=[])
        alias = f"__dsq{next(self._ip_counter)}"
        rte = RTE(alias, "subquery", subquery=derived,
                  columns={"__val": (f"{alias}.__val", val_expr.type),
                           **{f"__k{i}": (f"{alias}.__k{i}", k.type)
                              for i, k in enumerate(inner_keys)}})
        outer_bq.rtable.append(rte)
        outer_bq.join_order.append(JoinStep(len(outer_bq.rtable) - 1,
                                            "inner"))
        for i, ok in enumerate(outer_keys):
            outer_bq.where.append(E.Cmp("=", ok,
                                        E.Col(f"{alias}.__k{i}",
                                              inner_keys[i].type)))
        return E.Col(f"{alias}.__val", val_expr.type)

    # -- aggregation & projection ------------------------------------------
    def _plan_agg_project(self, bq: BoundQuery, plan: P.PhysNode):
        targets = bq.targets
        out_names = [n for n, _ in targets]

        if bq.has_aggs:
            plan, repl = self._plan_aggregate(bq, plan)
            proj = [(n, rewrite(e, repl)) for n, e in targets]
            having = [rewrite(h, repl) for h in bq.having]
            if having:
                plan = P.Filter(plan, having)
            order = [(rewrite(o, repl), d) for o, d in bq.order_by]
        else:
            proj = list(targets)
            order = list(bq.order_by)

        # window functions evaluate over the (post-aggregate) row set;
        # each distinct call becomes a computed __winN column
        wins: list[tuple[str, E.Expr]] = []

        def wrepl(x: E.Expr):
            if isinstance(x, E.WindowCall):
                for wname, wc in wins:
                    if wc == x:
                        return E.Col(wname, x.type)
                wname = f"__win{len(wins)}"
                wins.append((wname, x))
                return E.Col(wname, x.type)
            return None

        if any(isinstance(x, E.WindowCall)
               for _, e in proj for x in E.walk(e)) or \
           any(isinstance(x, E.WindowCall)
               for o, _ in order for x in E.walk(o)):
            proj = [(n, rewrite(e, wrepl)) for n, e in proj]
            order = [(rewrite(o, wrepl), d) for o, d in order]
            plan = P.Window(plan, wins)

        # pgvector pattern: ORDER BY vec <metric> 'q' LIMIT k over a plain
        # scan -> one fused AnnSearch node (top-k on device)
        ann = self._try_ann_search(bq, plan, proj, order)
        if ann is not None:
            return ann, out_names

        proj_node = P.Project(plan, proj)
        plan = proj_node

        if bq.distinct:
            plan = P.Agg(plan, [(n, E.Col(n, e.type)) for n, e in proj], [],
                         "single")

        if order:
            # sort keys over projected outputs; add hidden columns if needed
            keys = []
            extra = []
            for oe, desc in order:
                hit = None
                for n, e in proj:
                    if e == oe:
                        hit = (E.Col(n, e.type), desc)
                        break
                if hit is None:
                    hname = f"__sort{len(extra)}"
                    extra.append((hname, oe))
                    hit = (E.Col(hname, oe.type), desc)
                keys.append(hit)
            if extra:
                if bq.distinct:
                    raise PlanError("ORDER BY expression not in DISTINCT "
                                    "select list")
                proj_node.outputs = proj + extra
            plan = P.Sort(plan, keys,
                          limit=(bq.limit + (bq.offset or 0))
                          if bq.limit is not None else None)
        if bq.limit is not None or bq.offset:
            plan = P.Limit(plan, bq.limit, bq.offset or 0)
        return plan, out_names

    def _try_ann_search(self, bq, plan, proj, order):
        if (bq.has_aggs or bq.distinct or bq.limit is None or bq.offset
                or len(order) != 1 or order[0][1]):
            return None
        oe = order[0][0]
        if not isinstance(oe, E.DistExpr):
            return None
        # peel Filter wrappers down to a bare SeqScan
        filters = []
        node = plan
        while isinstance(node, P.Filter):
            filters = node.quals + filters
            node = node.child
        if not isinstance(node, P.SeqScan):
            return None
        filters = list(node.filters) + filters
        outputs = list(proj)
        dist_name = next((n for n, e in outputs if e == oe), None)
        if dist_name is None:
            dist_name = "__dist"
            outputs = outputs + [(dist_name, oe)]
        return P.AnnSearch(table=node.table, alias=node.alias,
                           filters=filters, outputs=outputs,
                           vec_col=oe.col.name, metric=oe.metric,
                           query=oe.query, k=bq.limit,
                           dist_name=dist_name)

    def _plan_aggregate(self, bq: BoundQuery, plan: P.PhysNode):
        group_keys = [(f"__gk{i}", g) for i, g in enumerate(bq.group_by)]
        aggs: list[tuple[str, E.AggCall]] = []
        # dedupe structurally: the same aggregate referenced from targets
        # and ORDER BY/HAVING may be distinct (but equal) objects
        agg_names: list[tuple[E.AggCall, str]] = []

        def find(x):
            for a, nm in agg_names:
                if a == x:
                    return nm
            return None

        def collect(e: E.Expr):
            for x in E.walk(e):
                if isinstance(x, E.AggCall) and find(x) is None:
                    name = f"__agg{len(aggs)}"
                    aggs.append((name, x))
                    agg_names.append((x, name))

        for _, e in bq.targets:
            collect(e)
        for h in bq.having:
            collect(h)
        for o, _ in bq.order_by:
            collect(o)

        plan = P.Agg(plan, group_keys, aggs, "single")

        def repl(x: E.Expr):
            if isinstance(x, E.AggCall):
                return E.Col(find(x), x.type)
            for name, g in group_keys:
                if x == g:
                    return E.Col(name, g.type)
            return None
        return plan, repl


class _RenameHelper:
    """Wrap a subquery plan so its outputs carry alias-qualified names."""
    @staticmethod
    def wrap(sub_plan: P.PhysNode, rte: RTE, filters) -> P.PhysNode:
        outs = []
        for plain, (qname, t) in rte.columns.items():
            outs.append((qname, E.Col(plain, t)))
        p = P.Project(sub_plan, outs)
        if filters:
            return P.Filter(p, filters)
        return p


def _rename_outputs(plan: P.PhysNode, sub: BoundQuery,
                    alias: str) -> P.PhysNode:
    outs = [(f"{alias}.{n}", E.Col(n, e.type)) for n, e in sub.targets]
    return P.Project(plan, outs)
