"""Cluster-wide observability: distributed trace context, wait-event
accounting, a live activity view, and a failure flight recorder.

Reference analogs: the (trace-id carrying) remote instrumentation that
explain_dist.c ships back to the coordinator, pg_stat_activity's
wait_event/wait_event_type columns, and the forensic surface a core
dump + log_min_error_statement gives a postgres operator — rebuilt for
the TPU engine's thread-per-session, RPC-per-fragment shape.

Three legs:

- **Trace context** (`inject`/`absorb`/`server_span`): the CN stamps a
  ``_xray`` key ({tid}) onto every outbound wire msg dict (backward
  compatible — servers that don't know it ignore it).  Servers open a
  bare root span around the handler body, so ALL existing server-side
  instrumentation (stage/execute/pool spans) nests under it for free,
  then piggy-back a byte-capped ``compact()`` of the subtree on the
  reply.  The CN grafts replies into the live trace: directly under
  the calling span when absorbed on the session thread, or into a
  pending map (``_REMOTE``) when absorbed on a dispatch worker thread
  — drained into the trace root at finish via ``on_trace_finish``.

- **Wait events** (`wait_event`/`mark`): a per-thread current-wait
  register plus cumulative log-bucket histograms (``otb_wait_ms``
  {event=...}) over the engine's named blocking points.  The register
  joins the activity view (below) so a live query shows WHAT it is
  waiting on, not just that it is slow.

- **Flight recorder** (`flight`): guard-rail trips (quarantine,
  statement timeout, OOM downshift, breaker trip, poison bisection)
  snapshot a postmortem JSON bundle — trace tree (remote subtrees
  included), wait profile, recent guard transitions, counter snapshot
  — into a bounded ring and, when ``$OTB_FLIGHT_DIR`` is set, onto
  disk.  Retrievable over the wire via the CN ``flight`` op.

Everything here is fail-open: a broken flight write or a malformed
piggy-back must never abort a query, so the recording paths swallow
their own exceptions.  With ``OTB_TRACE=0`` the context functions take
the shared-NULL fast path (no dict writes, no allocation).

Env vars: ``OTB_XRAY_MAX_BYTES`` (piggy-back subtree cap, default
8192), ``OTB_FLIGHT_DIR`` (bundle directory, empty = ring only),
``OTB_FLIGHT_RING`` (bundle ring size, default 32).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional
from ..utils import locks
from . import trace as _trace
from .metrics import REGISTRY

MAX_BYTES = int(os.environ.get("OTB_XRAY_MAX_BYTES", "8192") or "8192")
FLIGHT_DIR = os.environ.get("OTB_FLIGHT_DIR", "") or ""
FLIGHT_RING = int(os.environ.get("OTB_FLIGHT_RING", "32") or "32")

_TLS = threading.local()                # .tid: propagated trace id

_RLOCK = locks.Lock("obs.xray._RLOCK")
# trace_id -> [span dict subtrees pending graft]
_REMOTE: dict = {}                      # guarded_by: _RLOCK
_REMOTE_TRACES = 64                     # distinct in-flight traces kept
_REMOTE_SPANS = 64                      # subtrees kept per trace


# ---------------------------------------------------------------------------
# trace context: client side
# ---------------------------------------------------------------------------

def _current_tid() -> Optional[str]:
    qt = _trace.current_trace()
    if qt is not None:
        return qt.trace_id
    return getattr(_TLS, "tid", None)


def capture() -> Optional[str]:
    """Snapshot this thread's trace context for hand-off to a worker
    thread (the dispatch pool fans fragments out on threads that have
    no span stack of their own)."""
    return _current_tid()


class _Propagated:
    __slots__ = ("tid", "_prev")

    def __init__(self, tid):
        self.tid = tid
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_TLS, "tid", None)
        _TLS.tid = self.tid
        return self

    def __exit__(self, et, ev, tb):
        _TLS.tid = self._prev
        return False


def propagated(tid: Optional[str]) -> _Propagated:
    """Install a captured trace context on a worker thread for the
    duration of the block — `inject`/`absorb` then correlate the
    worker's RPCs with the originating query."""
    return _Propagated(tid)


def inject(msg: dict) -> dict:
    """Stamp the active trace context onto an outbound wire msg.
    Disabled tracing / no active trace → the msg is untouched (the
    shared-NULL fast path: one attr read, no allocation)."""
    if _trace.ENABLED:
        tid = _current_tid()
        if tid:
            msg["_xray"] = {"tid": tid}
    return msg


def absorb(resp, node: str = "", op: str = "") -> None:
    """Strip a reply's piggy-backed span subtree and graft it into the
    live trace.  On the session thread the subtree nests under the
    calling span (so remote `execute` never double-counts against the
    CN-observed RPC span); on a worker thread it parks in the pending
    map and is grafted at trace finish."""
    if not isinstance(resp, dict):
        return
    d = resp.pop("_xray", None)
    if not isinstance(d, dict):
        return
    sub = d.get("span")
    if not isinstance(sub, dict):
        return
    wrap = {"name": "remote", "ms": float(sub.get("ms") or 0.0),
            "attrs": {"node": node, "op": op}, "children": [sub]}
    if _trace.active():
        _trace.graft(wrap)
        return
    tid = d.get("tid") or getattr(_TLS, "tid", None)
    if not tid:
        return
    with _RLOCK:
        lst = _REMOTE.setdefault(tid, [])
        if len(lst) < _REMOTE_SPANS:
            lst.append(wrap)
        while len(_REMOTE) > _REMOTE_TRACES:     # oldest trace out
            _REMOTE.pop(next(iter(_REMOTE)))


def on_trace_finish(qt) -> None:
    """trace._finish hook: drain this trace's pending remote subtrees
    (absorbed on worker threads, where no span stack exists) into the
    finished tree so the ring/slow-log/flight views see them."""
    with _RLOCK:
        pend = _REMOTE.pop(qt.trace_id, None)
    if pend:
        for d in pend:
            try:
                qt.root.children.append(_trace.span_from_dict(d))
            except Exception:
                pass                  # a bad subtree never breaks finish


def peek_remote(tid: Optional[str]) -> list:
    """Pending remote subtrees for a still-open trace (EXPLAIN ANALYZE
    reads these before finish grafts them)."""
    if not tid:
        return []
    with _RLOCK:
        return [dict(d) for d in _REMOTE.get(tid, ())]


# ---------------------------------------------------------------------------
# trace context: server side
# ---------------------------------------------------------------------------

class _ServerSpan:
    """Handler-scope span: opened when the inbound msg carries trace
    context, so every span the server's own code opens nests under it;
    `attach()` piggy-backs the byte-capped subtree on the reply."""

    __slots__ = ("tid", "root", "_op", "_node")

    def __init__(self, msg, op: str, node: str = ""):
        ctx = msg.get("_xray") if isinstance(msg, dict) else None
        self.tid = ctx.get("tid") if isinstance(ctx, dict) else None
        self.root = None
        self._op = op
        self._node = node

    def __enter__(self):
        if self.tid and _trace.ENABLED:
            self.root = _trace.push_root("server", op=self._op,
                                         node=self._node)
        return self

    def __exit__(self, et, ev, tb):
        if self.root is not None:
            _trace.pop_root(self.root)
        return False

    # manual protocol for handler loops where the reply is built
    # across several suites and a `with` block would be awkward
    def open(self) -> "_ServerSpan":
        return self.__enter__()

    def close(self) -> None:
        self.__exit__(None, None, None)

    def attach(self, resp) -> None:
        if self.root is not None and isinstance(resp, dict):
            try:
                resp["_xray"] = {
                    "tid": self.tid,
                    "span": compact(self.root.to_dict(), MAX_BYTES)}
            except Exception:
                pass                  # never let tracing break a reply


def server_span(msg, op: str, node: str = "") -> _ServerSpan:
    return _ServerSpan(msg, op, node)


def compact(d: dict, max_bytes: int = MAX_BYTES) -> dict:
    """Shrink a span dict under `max_bytes` of JSON by progressively
    capping fan-out and depth; degenerates to a bare root."""
    def size(x) -> int:
        return len(json.dumps(x))

    if size(d) <= max_bytes:
        return d
    for width, depth in ((8, 8), (4, 6), (2, 4), (1, 2), (0, 0)):
        _prune(d, width, depth)
        if size(d) <= max_bytes:
            return d
    return {"name": str(d.get("name", "server")),
            "ms": float(d.get("ms") or 0.0),
            "attrs": {"truncated": True}}


def _prune(d: dict, width: int, depth: int) -> None:
    ch = d.get("children")
    if not ch:
        return
    if depth <= 0 or width <= 0:
        dropped = len(ch)
        d.pop("children", None)
        d.setdefault("attrs", {})["dropped"] = dropped
        return
    if len(ch) > width:
        d.setdefault("attrs", {})["dropped"] = len(ch) - width
        d["children"] = ch = ch[:width]
    for c in ch:
        _prune(c, width, depth - 1)


# ---------------------------------------------------------------------------
# per-DN remote phase rollup (EXPLAIN ANALYZE / bench --trace)
# ---------------------------------------------------------------------------

def remote_rows(qt=None) -> list:
    """[(node, {phase: ms, server_ms, rpcs})] aggregated from shipped
    subtrees — grafted ones plus any still pending for this trace."""
    qt = qt or _trace.current_trace() or _trace.last_trace()
    if qt is None:
        return []
    dicts = []
    work = [qt.root]
    while work:
        s = work.pop()
        for c in s.children:
            if c.name == "remote":
                dicts.append(c.to_dict())
            else:
                work.append(c)
    dicts.extend(peek_remote(getattr(qt, "trace_id", None)))
    agg: dict = {}
    for d in dicts:
        node = str((d.get("attrs") or {}).get("node") or "?")
        a = agg.setdefault(node, {"rpcs": 0})
        a["rpcs"] += 1
        stack = list(d.get("children") or ())
        while stack:
            c = stack.pop()
            nm = c.get("name")
            if nm in _trace.PHASES:
                # outermost-only, matching QueryTrace.phase_ms
                a[nm] = a.get(nm, 0.0) + float(c.get("ms") or 0.0)
            else:
                if nm == "server":
                    a["server_ms"] = a.get("server_ms", 0.0) \
                        + float(c.get("ms") or 0.0)
                stack.extend(c.get("children") or ())
    return sorted(agg.items())


# ---------------------------------------------------------------------------
# wait events
# ---------------------------------------------------------------------------

_WLOCK = locks.Lock("obs.xray._WLOCK")
# thread ident -> (event, started)
_WAITING: dict = {}                     # guarded_by: _WLOCK
# event names ever seen
_EVENTS: set = set()                    # guarded_by: _WLOCK


class _WaitCtx:
    __slots__ = ("event", "_t0", "_prev")

    def __init__(self, event: str):
        self.event = event
        self._t0 = 0.0
        self._prev = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        ident = threading.get_ident()
        with _WLOCK:
            self._prev = _WAITING.get(ident)    # nested waits restore
            _WAITING[ident] = (self.event, time.time())
            _EVENTS.add(self.event)
        return self

    def __exit__(self, et, ev, tb):
        ms = (time.perf_counter() - self._t0) * 1e3
        ident = threading.get_ident()
        with _WLOCK:
            if self._prev is None:
                _WAITING.pop(ident, None)
            else:
                _WAITING[ident] = self._prev
        REGISTRY.histogram("otb_wait_ms", event=self.event).observe(ms)
        if _trace.active():
            _trace.event("wait", event=self.event, ms=round(ms, 4))
        return False


def wait_event(event: str, **detail) -> _WaitCtx:
    """Name a blocking wait: registers the event as this thread's
    current wait (otb_stat_activity joins on it) and folds the wall
    time into the ``otb_wait_ms{event=...}`` histogram on exit.
    `detail` kwargs are accepted for call-site documentation only."""
    return _WaitCtx(event)


def mark(event: str, **detail) -> None:
    """An instantaneous wait observation — e.g. a breaker-open
    fail-fast, which rejects instead of blocking but still belongs in
    the wait profile."""
    with _WLOCK:
        _EVENTS.add(event)
    REGISTRY.histogram("otb_wait_ms", event=event).observe(0.0)
    if _trace.active():
        _trace.event("wait", event=event, ms=0.0)


def wait_rows() -> list:
    """(event, count, total_ms, p50, p95, p99) — otb_wait_events."""
    with _WLOCK:
        events = sorted(_EVENTS)
    rows = []
    for e in events:
        h = REGISTRY.histogram("otb_wait_ms", event=e)
        rows.append((e, int(h.count), float(h.sum),
                     h.quantile(0.5), h.quantile(0.95),
                     h.quantile(0.99)))
    return rows


def current_wait(ident) -> str:
    with _WLOCK:
        w = _WAITING.get(ident)
    return w[0] if w else ""


# ---------------------------------------------------------------------------
# activity view (otb_stat_activity)
# ---------------------------------------------------------------------------

_AIDS = itertools.count(1)
_ALOCK = locks.Lock("obs.xray._ALOCK")
# aid -> row dict
_ACTIVITY: dict = {}                    # guarded_by: _ALOCK


def activity_begin(sql: str, cancel=None, trace_id: str = "") -> int:
    """Register a live statement; returns its activity id (the cancel
    handle).  Caller owns the matching `activity_end`."""
    aid = next(_AIDS)
    with _ALOCK:
        _ACTIVITY[aid] = {"aid": aid, "sql": (sql or "")[:200],
                          "state": "queued", "t0": time.time(),
                          "thread": threading.get_ident(),
                          "cancel": cancel,
                          "trace_id": trace_id or ""}
    return aid


def activity_state(aid: int, state: str, thread=None) -> None:
    with _ALOCK:
        a = _ACTIVITY.get(aid)
        if a is not None:
            a["state"] = state
            if thread is not None:
                a["thread"] = thread


def activity_end(aid: int) -> None:
    with _ALOCK:
        _ACTIVITY.pop(aid, None)


def activity_cancel(aid: int) -> bool:
    """Fire a live statement's cancel handle (pg_cancel_backend's
    moral equivalent).  True if the statement was live and cancelable."""
    with _ALOCK:
        a = _ACTIVITY.get(aid)
        ev = a.get("cancel") if a else None
    if ev is None:
        return False
    ev.set()
    return True


def activity_rows() -> list:
    """(aid, state, wait_event, age_ms, cancelable, trace_id, sql) —
    one row per live statement, current wait joined by thread."""
    now = time.time()
    with _ALOCK:
        acts = [dict(a) for a in _ACTIVITY.values()]
    rows = []
    for a in sorted(acts, key=lambda a: a["aid"]):
        rows.append((a["aid"], a["state"], current_wait(a["thread"]),
                     (now - a["t0"]) * 1e3,
                     a["cancel"] is not None, a["trace_id"], a["sql"]))
    return rows


# ---------------------------------------------------------------------------
# guard-transition ring + flight recorder
# ---------------------------------------------------------------------------

_GLOCK = locks.Lock("obs.xray._GLOCK")
_GUARD_EVENTS: deque = deque(maxlen=256)    # guarded_by: _GLOCK

_FIDS = itertools.count(1)
_FLOCK = locks.Lock("obs.xray._FLOCK")
_FLIGHTS: deque = deque(maxlen=max(FLIGHT_RING, 1))  # guarded_by: _FLOCK


def guard_event(kind: str, **detail) -> None:
    """Record a guard transition (trip/shed/failover/quarantine...) in
    the bounded ring postmortem bundles snapshot, correlated with the
    active trace when there is one."""
    rec = {"ts": time.time(), "kind": kind}
    tid = _current_tid()
    if tid:
        rec["trace_id"] = tid
    for k, v in detail.items():
        rec[k] = v if isinstance(v, (str, int, float, bool,
                                     type(None))) else str(v)
    with _GLOCK:
        _GUARD_EVENTS.append(rec)


def guard_events() -> list:
    with _GLOCK:
        return [dict(r) for r in _GUARD_EVENTS]


def _counters_snapshot() -> dict:
    snap = {}
    try:
        for name, labels, kind, value in REGISTRY.samples():
            if kind != "counter":
                continue
            key = name
            if labels:
                key += "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"
            snap[key] = value
    except Exception:
        pass                          # a broken collector never breaks a flight
    return snap


def flight(kind: str, sig: str = "", **extras) -> Optional[dict]:
    """Emit a postmortem bundle: ring it, count it, and (when
    ``$OTB_FLIGHT_DIR`` is set) persist it as JSON.  Fail-open — the
    recorder must never turn an incident into a second failure."""
    try:
        qt = _trace.current_trace() or _trace.last_trace()
        tid, trace_d = "", None
        if qt is not None:
            tid = getattr(qt, "trace_id", "") or ""
            try:
                trace_d = qt.to_dict()
                pend = peek_remote(tid)
                if pend:
                    trace_d.setdefault("spans", {}) \
                        .setdefault("children", []).extend(pend)
            except Exception:
                trace_d = None
        bundle = {"event": "flight", "kind": kind, "ts": time.time(),
                  "trace_id": tid, "signature": sig,
                  "waits": [list(r) for r in wait_rows()],
                  "guard_events": guard_events(),
                  "counters": _counters_snapshot(),
                  "trace": trace_d}
        if extras:
            bundle["extras"] = dict(extras)
        # round-trip through JSON now: a bundle that can be ringed can
        # always be retrieved/persisted later
        bundle = json.loads(json.dumps(bundle, default=str))
        with _FLOCK:
            _FLIGHTS.append(bundle)
        REGISTRY.counter("otb_flight_bundles_total", kind=kind).inc()
        if FLIGHT_DIR:
            try:
                os.makedirs(FLIGHT_DIR, exist_ok=True)
                path = os.path.join(
                    FLIGHT_DIR,
                    f"flight-{kind}-{int(time.time() * 1e3)}"
                    f"-{next(_FIDS)}.json")
                with open(path, "w") as f:
                    json.dump(bundle, f, sort_keys=True)
            except OSError:
                pass                  # a full/readonly disk never aborts a query
        return bundle
    except Exception:
        return None


def flights() -> list:
    """Ringed postmortem bundles, oldest → newest (the CN `flight`
    wire op's backing)."""
    with _FLOCK:
        return [dict(b) for b in _FLIGHTS]
