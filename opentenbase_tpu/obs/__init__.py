"""Observability subsystem — query tracing, unified metrics, slow log.

Reference analog: the DN→CN runtime instrumentation behind EXPLAIN
ANALYZE (commands/explain_dist.c) plus the pgstat views
(pg_stat_activity / pg_stat_statements family).  Three pillars:

- ``obs.trace``  — per-query span trees (plan → stage → execute →
  exchange → finalize), a bounded ring of recent traces backing the
  ``otb_stat_query`` view, and an opt-in structured slow-query log.
- ``obs.metrics`` — one process-global registry of counters / gauges /
  log-bucket histograms; the engine's existing stat surfaces
  (plancache, bufferpool, EXEC_STATS) register collectors into it, and
  it serves the ``otb_metrics`` view + Prometheus text exposition.
- EXPLAIN ANALYZE (exec/session.py, exec/dist_session.py) runs the
  statement under tracing and annotates the plan printout with actual
  rows / ms / cache behavior.

Purity contract: nothing in this package may be called from code
reachable from a jit/shard_map trace root — instrumentation lives at
the HOST boundaries (session dispatch, staging, program call sites,
materialization), never inside compiled programs.  The otblint
``obs-purity`` pass enforces this statically.
"""

from . import metrics, trace  # noqa: F401
