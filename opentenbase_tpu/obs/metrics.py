"""Unified metrics registry — counters, gauges, log-bucket histograms.

Reference analog: the pgstat shared-memory counters behind the
``pg_stat_*`` views, plus the cumulative-histogram exposition format
popularized by Prometheus.

One process-global ``REGISTRY``:

- native metrics: ``counter()/gauge()/histogram()`` get-or-create by
  (name, labels).  Histograms use FIXED log-scale latency buckets
  (factor 2^1/4 from 1 µs to ~4.6 min) so p50/p95/p99 estimation
  needs no stored samples — quantile error is bounded by one bucket
  width (≤ ~19 %).
- registered collectors: the engine's existing stat surfaces
  (exec/plancache, storage/bufferpool, executor EXEC_STATS) register a
  sample generator at import instead of growing another bespoke locked
  dict — the registry is the single pane of glass that the
  ``otb_metrics`` view and ``metrics_text()`` exposition read.

Thread-safety: the registry dict is guarded by ``_LOCK``; each metric
carries its own lock so hot-path ``inc``/``observe`` never contend on
the registry.  Collector generators must do their own locking (they
already read under their subsystem's lock).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Iterable, Optional
from ..utils import locks

# fixed log-scale bucket bounds (ms): 2^-10 .. 2^18, quarter-power steps
_BUCKET_LO_EXP = -10.0
_BUCKET_STEP = 0.25
_NBUCKETS = 113                 # [2^-10, 2^18) in 2^0.25 steps, + overflow
BUCKET_BOUNDS = tuple(
    2.0 ** (_BUCKET_LO_EXP + _BUCKET_STEP * i) for i in range(_NBUCKETS))


class Counter:
    kind = "counter"
    __slots__ = ("name", "labels", "_v", "_lock")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._v = 0.0
        self._lock = locks.Lock("obs.metrics.metric._lock")

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:   # otblint: eager-only
        return self._v

    def samples(self):
        yield (self.name, self.labels, "counter", self._v)


class Gauge:
    kind = "gauge"
    __slots__ = ("name", "labels", "_v", "_lock")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._v = 0.0
        self._lock = locks.Lock("obs.metrics.metric._lock")

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:   # otblint: eager-only
        return self._v

    def samples(self):
        yield (self.name, self.labels, "gauge", self._v)


class Histogram:
    """Fixed log-bucket histogram: O(1) observe, O(buckets) quantile,
    zero sample storage."""

    kind = "histogram"
    __slots__ = ("name", "labels", "counts", "count", "sum", "_lock")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self.counts = [0] * (_NBUCKETS + 1)    # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self._lock = locks.Lock("obs.metrics.metric._lock")

    @staticmethod
    def _bucket(v: float) -> int:
        if v <= BUCKET_BOUNDS[0]:
            return 0
        i = int((math.log2(v) - _BUCKET_LO_EXP) / _BUCKET_STEP) + 1
        return min(i, _NBUCKETS)

    def observe(self, v: float) -> None:
        i = self._bucket(v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v

    def quantile(self, q: float) -> float:
        """Estimated q-quantile: geometric midpoint of the bucket where
        the cumulative count crosses q·total."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                if i == 0:
                    return BUCKET_BOUNDS[0]
                lo = BUCKET_BOUNDS[i - 1]
                hi = BUCKET_BOUNDS[min(i, _NBUCKETS - 1)]
                return math.sqrt(lo * hi)
        return BUCKET_BOUNDS[-1]

    def samples(self):
        yield (self.name + "_count", self.labels, "histogram", self.count)
        yield (self.name + "_sum", self.labels, "histogram", self.sum)
        for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            yield (self.name + "_" + tag, self.labels, "histogram",
                   self.quantile(q))


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    def __init__(self):
        self._lock = locks.Lock("obs.metrics.Registry._lock")
        self._metrics: dict = {}        # (name, labels) -> metric
        self._collectors: dict = {}     # name -> sample generator fn

    def _get(self, kind: str, name: str, labels: dict):
        lt = tuple(sorted(labels.items()))
        key = (name, lt)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = _KINDS[kind](name, lt)
            elif m.kind != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    def register_collector(self, name: str,
                           fn: Callable[[], Iterable]) -> None:
        """Idempotent: a subsystem exports its live counters by name.
        `fn` yields (metric_name, labels_dict, value) samples."""
        with self._lock:
            self._collectors[name] = fn

    # ------------------------------------------------------------------
    def samples(self):
        """Every sample, native + collected:
        (name, labels_tuple, kind, value)."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors.items())
        for m in sorted(metrics, key=lambda m: (m.name, m.labels)):
            yield from m.samples()
        for _cname, fn in sorted(collectors):
            try:
                rows = list(fn())
            except Exception:
                continue            # a broken collector never breaks the scrape
            for name, labels, value in rows:
                yield (name, tuple(sorted(labels.items())), "gauge",
                       float(value))

    def rows(self):
        """(name, labels_text, kind, value) rows — the otb_metrics view."""
        for name, labels, kind, value in self.samples():
            lbl = ",".join(f"{k}={v}" for k, v in labels)
            yield (name, lbl, kind, float(value))

    def text(self) -> str:
        """Prometheus-style text exposition.  Histograms additionally
        emit cumulative ``_bucket`` lines (every 4th bound + +Inf, so
        the bucket count stays scrape-friendly)."""
        out = []
        typed = set()
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: (m.name, m.labels))
        for m in metrics:
            if m.name not in typed:
                typed.add(m.name)
                out.append(f"# TYPE {m.name} {m.kind}")
            if m.kind == "histogram":
                with m._lock:
                    counts = list(m.counts)
                    count, total = m.count, m.sum
                cum = 0
                for i, c in enumerate(counts):
                    cum += c
                    if i % 4 == 0 and i < _NBUCKETS:
                        out.append(_sample_line(
                            m.name + "_bucket",
                            m.labels + (("le", f"{BUCKET_BOUNDS[i]:g}"),),
                            cum))
                out.append(_sample_line(
                    m.name + "_bucket", m.labels + (("le", "+Inf"),),
                    count))
                out.append(_sample_line(m.name + "_sum", m.labels, total))
                out.append(_sample_line(m.name + "_count", m.labels,
                                        count))
            else:
                out.append(_sample_line(m.name, m.labels, m.value))
        with self._lock:
            collectors = sorted(self._collectors.items())
        for _cname, fn in collectors:
            try:
                rows = list(fn())
            except Exception:
                continue            # a broken collector never breaks the scrape
            for name, labels, value in rows:
                if name not in typed:
                    typed.add(name)
                    out.append(f"# TYPE {name} gauge")
                out.append(_sample_line(
                    name, tuple(sorted(labels.items())), float(value)))
        return "\n".join(out) + "\n"


def _escape_label(v) -> str:
    """Prometheus text-format label escaping: backslash, double quote,
    and newline must be escaped inside quoted label values."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _sample_line(name: str, labels: tuple, value) -> str:
    if labels:
        lbl = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
        return f"{name}{{{lbl}}} {value:g}"
    return f"{name} {value:g}"


REGISTRY = Registry()


def observe_query(qt) -> None:
    """Trace-finish hook: fold one QueryTrace into the registry."""
    tier = qt.tier or "single"
    REGISTRY.counter("otb_queries_total", tier=tier).inc()
    REGISTRY.histogram("otb_query_ms", tier=tier).observe(
        max(qt.total_ms, 0.0))
    for ph in ("plan", "stage", "execute", "finalize"):
        ms = qt.phase_ms(ph)
        if ms > 0:
            REGISTRY.histogram("otb_phase_ms", phase=ph).observe(ms)
