"""Query tracing — lightweight span trees over the read path.

Reference analog: the per-node InstrumentOption timers that feed
EXPLAIN ANALYZE (instrument.c) generalized to the whole CN pipeline:
parse+plan, plancache hit/compile, bufferpool staging, fused/mesh
program dispatch, exchanges, host gather/finalize.

Design constraints (TPU-first):
- Device phases are timed ONLY at the existing materialization /
  sync boundaries (program-call overflow ``device_get``s, ``DBatch``
  materialization, gather conversion) — instrumentation never adds a
  host sync, and never appears inside a traced closure (enforced by
  the otblint ``obs-purity`` pass).
- ~zero overhead when disabled (``OTB_TRACE=0``): ``span()`` returns a
  shared no-op singleton, no Span objects are allocated, no locks are
  taken on the statement path.
- Thread-safe by construction: the active span stack is thread-local
  (each CN server session is a thread); only trace FINISH touches the
  shared ring, under ``_LOCK``.

Env vars: ``OTB_TRACE`` (default on), ``OTB_SLOW_MS`` (slow-query log
threshold, 0 = off), ``OTB_TRACE_RING`` (recent-trace ring size).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Optional
from ..utils import locks

ENABLED = os.environ.get("OTB_TRACE", "1").strip().lower() \
    not in ("0", "off", "false")
SLOW_MS = float(os.environ.get("OTB_SLOW_MS", "0") or "0")
SLOW_STREAM = sys.stderr        # swappable in tests / by embedders
RING_CAP = int(os.environ.get("OTB_TRACE_RING", "64") or "64")

_TLS = threading.local()        # .stack: list[Span], .trace: QueryTrace
_LOCK = locks.Lock("obs.trace._LOCK")
_RING: deque = deque(maxlen=RING_CAP)   # guarded_by: _LOCK
_LAST: list = [None]                    # guarded_by: _LOCK
_IDS = itertools.count(1)
# per-process trace-id prefix: qids restart at 1 in every process, so
# cluster-wide correlation (slow log ↔ flight bundle ↔ shipped span)
# needs a process-unique component
_SEED = os.urandom(4).hex()

# canonical phase names summarized per query (otb_stat_query columns)
PHASES = ("plan", "stage", "execute", "exchange", "finalize")


class Span:
    """One timed region.  Context-manager protocol only: creation via
    ``span()`` attaches nothing — ``__enter__`` pushes onto the
    thread's stack, ``__exit__`` pops and stamps ``ms``."""

    __slots__ = ("name", "attrs", "ms", "children", "_t0")

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs = attrs if attrs else {}
        self.ms = 0.0
        self.children: list = []
        self._t0 = 0.0

    def set(self, **kw) -> "Span":
        self.attrs.update(kw)
        return self

    def __enter__(self) -> "Span":
        st = _TLS.stack
        st[-1].children.append(self)
        st.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        self.ms = (time.perf_counter() - self._t0) * 1e3
        _TLS.stack.pop()
        return False

    def to_dict(self) -> dict:
        d = {"name": self.name, "ms": round(self.ms, 4)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class _NullSpan:
    """The disabled-path span: one shared instance, every operation a
    no-op — the zero-allocation fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        return False

    def set(self, **kw):
        return self


NULL_SPAN = _NullSpan()


def _stack() -> Optional[list]:
    return getattr(_TLS, "stack", None)


def active() -> bool:
    """True when a query trace is open on THIS thread."""
    return bool(getattr(_TLS, "stack", None))


def span(name: str, **attrs):
    """Open a child span under the current one.  Use as a context
    manager.  No active trace (or tracing disabled) → the shared
    no-op singleton."""
    st = getattr(_TLS, "stack", None)
    if not st:
        return NULL_SPAN
    return Span(name, attrs)


def event(name: str, **attrs) -> None:
    """Record a zero-duration child (cache hit/miss, retrace, upload)."""
    st = getattr(_TLS, "stack", None)
    if st:
        st[-1].children.append(Span(name, attrs))


def annotate(**kw) -> None:
    """Attach attributes to the innermost open span, if any."""
    st = getattr(_TLS, "stack", None)
    if st:
        st[-1].attrs.update(kw)


# ---------------------------------------------------------------------------
# cross-node helpers (obs/xray.py) — server-side bare roots + grafting
# ---------------------------------------------------------------------------

def push_root(name: str, **attrs) -> Span:
    """Open a span on THIS thread even without an active trace — a
    server handler thread has no QueryTrace; the bare root becomes the
    piggy-backed subtree's top.  Pair with ``pop_root``."""
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    sp = Span(name, attrs)
    if st:                           # nested server op: ride the stack
        st[-1].children.append(sp)
    st.append(sp)
    sp._t0 = time.perf_counter()
    return sp


def pop_root(sp: Span) -> Span:
    sp.ms = (time.perf_counter() - sp._t0) * 1e3
    st = getattr(_TLS, "stack", None)
    if st and st[-1] is sp:
        st.pop()
    return sp


def span_from_dict(d: dict) -> Span:
    """Rehydrate a shipped span subtree (inverse of Span.to_dict)."""
    sp = Span(str(d.get("name", "?")), dict(d.get("attrs") or {}))
    sp.ms = float(d.get("ms") or 0.0)
    sp.children = [span_from_dict(c) for c in d.get("children") or ()]
    return sp


def graft(d: dict) -> None:
    """Attach a shipped subtree under the current span (remote phase
    spans nest INSIDE the CN's RPC span, so ``phase_ms``'s
    outermost-only rule never double-counts them)."""
    st = getattr(_TLS, "stack", None)
    if st:
        st[-1].children.append(span_from_dict(d))


class QueryTrace:
    """One statement's span tree plus identity/summary fields."""

    __slots__ = ("qid", "signature", "root", "tier", "rows", "started",
                 "trace_id")

    def __init__(self, signature: str):
        self.qid = next(_IDS)
        self.signature = signature
        self.root = Span("query")
        self.tier = ""
        self.rows = 0
        self.started = time.time()
        self.trace_id = f"{_SEED}-{self.qid:x}"

    @property
    def total_ms(self) -> float:
        return self.root.ms

    def phase_ms(self, name: str) -> float:
        """Sum of ms over spans named `name`, counting only the
        outermost of any nested same-name runs."""
        total = 0.0
        work = [self.root]
        while work:
            s = work.pop()
            for c in s.children:
                if c.name == name:
                    total += c.ms
                else:
                    work.append(c)
        return total

    def sum_attr(self, span_name: str, key: str) -> float:
        total = 0.0
        work = [self.root]
        while work:
            s = work.pop()
            if s.name == span_name:
                total += float(s.attrs.get(key, 0) or 0)
            work.extend(s.children)
        return total

    def count_events(self, span_name: str, **match) -> int:
        n = 0
        work = [self.root]
        while work:
            s = work.pop()
            if s.name == span_name and all(
                    s.attrs.get(k) == v for k, v in match.items()):
                n += 1
            work.extend(s.children)
        return n

    def summary(self) -> dict:
        d = {
            "qid": self.qid,
            "trace_id": self.trace_id,
            "signature": self.signature,
            "tier": self.tier or "single",
            "total_ms": self.total_ms,
            "rows": self.rows,
            "bytes_staged": int(self.sum_attr("upload", "bytes")),
            "bytes_materialized": int(
                self.sum_attr("finalize", "bytes")),
            "pool_hits": self.count_events("pool", hit=True),
            "pool_misses": self.count_events("pool", hit=False),
        }
        for ph in PHASES:
            d[f"{ph}_ms"] = self.phase_ms(ph)
        # overlap-adjusted staging (otbpipe): wall time the dispatch
        # path actually WAITED on staging.  Producers mark staging that
        # ran behind device compute with an `overlapped_ms` attr on the
        # stage span; without overlap this equals stage_ms, so the new
        # pipeline doesn't misread as staging going to zero.
        d["stage_wait_ms"] = max(
            d["stage_ms"] - self.sum_attr("stage", "overlapped_ms"),
            0.0)
        return d

    def to_dict(self) -> dict:
        d = self.summary()
        d["spans"] = self.root.to_dict()
        return d


class _TraceCtx:
    """``trace_query`` context: opens a fresh QueryTrace unless one is
    already active on this thread (nested statements — triggers, the
    EXPLAIN ANALYZE inner run — ride the enclosing trace)."""

    __slots__ = ("signature", "owned")

    def __init__(self, signature: str):
        self.signature = signature
        self.owned = None

    def __enter__(self) -> Optional[QueryTrace]:
        if not ENABLED:
            return None
        st = _stack()
        if st is None:
            st = _TLS.stack = []
        if st:                       # nested: join the active trace
            return getattr(_TLS, "trace", None)
        qt = QueryTrace(self.signature)
        self.owned = qt
        _TLS.trace = qt
        st.append(qt.root)
        qt.root._t0 = time.perf_counter()
        return qt

    def __exit__(self, et, ev, tb):
        qt = self.owned
        if qt is not None:
            qt.root.ms = (time.perf_counter() - qt.root._t0) * 1e3
            _TLS.stack.pop()
            _TLS.trace = None
            _finish(qt, failed=et is not None)
        return False


class _NullTraceCtx:
    """Disabled-path trace context: one shared instance, yields None."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


_NULL_CTX = _NullTraceCtx()


def trace_query(signature: str = ""):
    if not ENABLED:
        return _NULL_CTX
    return _TraceCtx(signature)


def current_trace() -> Optional[QueryTrace]:
    """The trace open on this thread, else None."""
    return getattr(_TLS, "trace", None) if active() else None


def last_trace() -> Optional[QueryTrace]:
    """The most recently FINISHED trace (any thread)."""
    with _LOCK:
        return _LAST[0]


def recent() -> list:
    """Finished traces, oldest → newest (the otb_stat_query backing)."""
    with _LOCK:
        return list(_RING)


def _finish(qt: QueryTrace, failed: bool = False) -> None:
    try:
        # graft remote subtrees absorbed on worker threads BEFORE the
        # trace becomes visible in the ring / metrics / slow log
        from . import xray
        xray.on_trace_finish(qt)
    except Exception:
        pass                         # observability never fails a query
    with _LOCK:
        _RING.append(qt)
        _LAST[0] = qt
    from . import metrics
    metrics.observe_query(qt)
    if SLOW_MS > 0 and qt.total_ms >= SLOW_MS and not failed:
        metrics.REGISTRY.counter("otb_slow_queries_total").inc()
        rec = qt.summary()
        rec["event"] = "slow_query"
        try:
            SLOW_STREAM.write(json.dumps(rec, sort_keys=True) + "\n")
            SLOW_STREAM.flush()
        except (OSError, ValueError):
            pass                     # a closed log stream never aborts a query
