"""GTM proxy — connection concentrator between backends and the GTM.

Reference analog: src/gtm/proxy/proxy_main.c / proxy_thread.c (enabled
by the `enable_gtm_proxy` GUC): many backend connections multiplex onto
ONE upstream GTM connection, and concurrent GTS requests coalesce into
a single batched fetch — the GTM's critical section is a clock bump, so
the win is connection count and round trips, not compute.

Speaks exactly the GtmServer wire protocol on both sides: backends
point their GtmClient at the proxy and notice nothing.
"""

from __future__ import annotations

import queue
import socketserver
import threading
from typing import Optional

from ..net.wire import recv_msg, send_msg
from ..obs import xray
from .server import GtmClient


class _Pending:
    __slots__ = ("msg", "event", "resp")

    def __init__(self, msg):
        self.msg = msg
        self.event = threading.Event()
        self.resp: Optional[dict] = None


class GtmProxy:
    """TCP front end multiplexing backends onto one upstream client."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1", port: int = 0):
        self.upstream = GtmClient(upstream_host, upstream_port)
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self.batched_gts = 0     # observability: coalesced GTS fetches
        self.upstream_calls = 0
        proxy = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    if msg is None:
                        return
                    if proxy._stopping:
                        send_msg(self.request,
                                 {"error": "proxy shutting down"})
                        return
                    p = _Pending(msg)
                    proxy._q.put(p)
                    # the backend's GTS grant wait: the pump
                    # answers from one coalesced upstream round
                    with xray.wait_event("gts-grant"):
                        p.event.wait()
                    send_msg(self.request, p.resp)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._srv_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._pump_thread = threading.Thread(target=self._pump,
                                             daemon=True)
        self._stopping = False

    # ------------------------------------------------------------------
    def _pump(self):
        """Single drain loop owning the upstream connection (the
        reference's proxy worker thread).  Waiting GTS requests are
        answered from ONE gts_batch round trip."""
        while not self._stopping:
            try:
                # pump idle dequeue, not a query-visible stall
                first = self._q.get(timeout=0.2)  # otblint: disable=wait-discipline
            except queue.Empty:
                continue
            batch = [first]
            # opportunistic coalescing: everything already queued
            while True:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            gts_reqs = [p for p in batch if p.msg.get("op") == "gts"]
            others = [p for p in batch if p.msg.get("op") != "gts"]
            if gts_reqs:
                try:
                    self.upstream_calls += 1
                    if len(gts_reqs) == 1:
                        gts_reqs[0].resp = self.upstream.call(op="gts")
                    else:
                        self.batched_gts += len(gts_reqs)
                        ts = self.upstream.call(
                            op="gts_batch", n=len(gts_reqs))["ts"]
                        for p, t in zip(gts_reqs, ts):
                            p.resp = {"ts": t}
                except Exception as e:
                    for p in gts_reqs:
                        if p.resp is None:
                            p.resp = {"error": str(e)}
                for p in gts_reqs:
                    p.event.set()
            for p in others:
                try:
                    self.upstream_calls += 1
                    p.resp = self.upstream.call(**p.msg)
                except Exception as e:
                    p.resp = {"error": str(e)}
                p.event.set()

    # ------------------------------------------------------------------
    def start(self):
        self._srv_thread.start()
        self._pump_thread.start()
        return self

    def stop(self):
        self._stopping = True
        self._server.shutdown()
        self._server.server_close()
        # let the pump finish its in-flight upstream call, then fail any
        # stragglers so no handler blocks forever on event.wait().  The
        # handler rejects new work once _stopping is set; the second
        # drain pass catches anything that slipped past both checks
        self._pump_thread.join(timeout=5.0)
        import time as _time
        for _ in range(2):
            while True:
                try:
                    p = self._q.get_nowait()
                except queue.Empty:
                    break
                p.resp = {"error": "proxy shutting down"}
                p.event.set()
            _time.sleep(0.05)
        self.upstream.close()
