"""GTM standby — reserve-window shipping + promote.

Reference analog: src/gtm/main/gtm_standby.c + gtm_xlog.c walsender/
walreceiver threads and `gtm_ctl promote` (src/gtm/gtm_ctl).  Re-designed
around this GTM's persistence model: the primary already makes itself
crash-safe by persisting RESERVE-sized timestamp/txid windows before
issuing from them (gtm/server.py).  Replication therefore does not need
an xlog stream — shipping each persisted state snapshot to the standby
gives the standby exactly the primary's crash-recovery point.  Promote =
resume past the last shipped reserve window, the same rule the primary
itself uses after a crash, so a promoted standby can never re-issue a
timestamp or txid the old primary handed out (provided the ship was
synchronous — see `sync` below).

Wiring: pass ``ship=ship_to(host, port)`` (or ``ship=standby.apply`` in
process) to GtmCore; run a GtmStandbyServer next to the standby.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
from typing import Callable, Optional

from ..net.wire import recv_msg, send_msg
from .server import GtmCore
from ..obs import xray
from ..utils import locks


class GtmStandby:
    """Holds the latest shipped primary state; promotable to a GtmCore.

    ``apply`` is called with each persisted state snapshot (directly by
    an in-process primary, or by GtmStandbyServer for a TCP primary).
    The standby persists every snapshot to its own store before acking,
    so a synchronous primary + acked ship implies the promote point is
    durable here.
    """

    def __init__(self, store_path: Optional[str] = None):
        self._lock = locks.Lock("gtm.standby.GtmStandby._lock")
        self.store_path = store_path
        self._state: Optional[dict] = None
        self.applied = 0
        if store_path and os.path.exists(store_path):
            with open(store_path) as f:
                self._state = json.load(f)

    def apply(self, state: dict) -> None:
        with self._lock:
            self._state = state
            self.applied += 1
            if self.store_path:
                tmp = self.store_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(state, f)
                os.replace(tmp, self.store_path)

    def state(self) -> Optional[dict]:
        with self._lock:
            return dict(self._state) if self._state else None

    def promote(self, store_path: Optional[str] = None) -> GtmCore:
        """Become the primary: build a GtmCore resuming past the last
        shipped reserve window (the primary's own crash-recovery rule).
        The promoted core persists to ``store_path`` (default: the
        standby's own store)."""
        with self._lock:
            if self._state is None:
                raise RuntimeError("standby has no shipped state to "
                                   "promote from")
            path = store_path or self.store_path
            if path:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(self._state, f)
                os.replace(tmp, path)
                return GtmCore(path)
            # memory-only promote (tests): seed a core directly, from a
            # deep copy — the core must not mutate the standby's retained
            # snapshot (a re-promote after the core dies resumes from the
            # last SHIPPED state, not the dead core's)
            st = json.loads(json.dumps(self._state))
            core = GtmCore(None)
            core._ts = st["reserved_ts"]
            core._txid = st["reserved_txid"]
            core._sequences = st.get("sequences", {})
            core._prepared = st.get("prepared", {})
            core._persist_locked()
            return core


class GtmStandbyServer:
    """TCP front end for a GtmStandby: accepts `replicate` frames from
    the primary's ship hook, plus ping/stats for health checks."""

    def __init__(self, standby: GtmStandby, host: str = "127.0.0.1",
                 port: int = 0):
        self.standby = standby
        sb = standby

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    if msg is None:
                        return
                    op = msg.get("op")
                    try:
                        if op == "replicate":
                            sb.apply(msg["state"])
                            resp = {"ok": True, "applied": sb.applied}
                        elif op == "ping":
                            resp = {"pong": True, "applied": sb.applied}
                        elif op == "stats":
                            resp = {"state": sb.state(),
                                    "applied": sb.applied}
                        else:
                            resp = {"error": f"unknown op {op!r}"}
                    except Exception as e:
                        resp = {"error": str(e)}
                    send_msg(self.request, resp)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def ship_to(host: str, port: int, timeout: float = 5.0) -> Callable:
    """Build a ship hook for GtmCore: sends each persisted state to a
    GtmStandbyServer and waits for the ack (synchronous replication —
    the primary's _persist_locked fails if the standby didn't take it)."""
    state_lock = locks.Lock("gtm.standby.ship_to.state_lock")
    conn: list[Optional[socket.socket]] = [None]

    # state_lock IS the replication serializer: ships must reach the
    # standby in persist order, so the socket conversation happens
    # under it by design; the hold is bounded by the socket timeout
    def ship(state: dict) -> None:  # otblint: disable=lock-blocking
        with state_lock:
            if conn[0] is None:
                conn[0] = socket.create_connection((host, port),
                                                   timeout=timeout)
            try:
                # expect_reply: the standby owes an ack — a close here
                # is a failed ship, not an idle hangup (sync replication
                # must never report success it didn't get)
                with xray.wait_event("wal-ship"):
                    send_msg(conn[0], {"op": "replicate",
                                       "state": state})
                    resp = recv_msg(conn[0], expect_reply=True)
            except (ConnectionError, OSError):
                try:
                    conn[0].close()
                finally:
                    conn[0] = None
                raise
            if not resp.get("ok"):
                raise ConnectionError(f"standby rejected state: {resp}")

    return ship
