"""GTM — the global timestamp / transaction manager service.

Reference analog: src/gtm (GTM_ThreadMain main.c:3860, GTS issue
ProcessGetGTSCommand gtm_txn.c:1635, sequences gtm_seq.c, persistent store
gtm_store.c, standby streaming gtm_standby.c).  Re-designed host-side:

- A monotonic hybrid clock: GTS = max(last+1, wall_us) so timestamps are
  both monotone and loosely wall-aligned (the reference bumps a persisted
  base by a monotonic delta, gtm_txn.c:1434,1582).
- Runs in-process (centralized mode) or as a threaded TCP server with a
  tiny length-prefixed msgpack-free protocol (net/wire.py).
- Persistence: periodic state snapshots + a reserve window so a crash can
  never hand out a timestamp twice (the reference reserves GTS ranges in
  its mmap'd store for the same reason).
- Standby: see gtm/standby.py — a secondary GTM polls the primary's
  persisted reserve windows and promotes by resuming past them.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from typing import Optional

from ..net.wire import recv_msg, send_msg
from ..obs import xray
from ..utils import locks

RESERVE = 1_000_000  # timestamps reserved ahead per persistence write


class GtmCore:
    """The clock + txid + sequence state machine (shared by in-process and
    server modes)."""

    def __init__(self, store_path: Optional[str] = None,
                 ship=None, sync_ship: bool = True):
        """``ship``: optional hook called with each persisted state
        snapshot (reserve-window replication to a GtmStandby — see
        gtm/standby.py).  With ``sync_ship`` (the reference's synchronous
        standby), a failed ship blocks allocation past the last shipped
        window, so a promoted standby can never re-issue; async mode
        keeps serving and flags ``standby_ok`` False instead."""
        self._lock = locks.Lock("gtm.server.GtmCore._lock")
        self._ts = 100
        self._txid = 1
        self._sequences: dict[str, dict] = {}
        self._prepared: dict[str, dict] = {}   # gid -> info (2PC registry)
        # cluster barriers: name -> {gts, wall} (reference: the barrier
        # records CREATE BARRIER leaves for PITR, pgxc/barrier/barrier.c;
        # the GTM copy is the restore authority)
        self._barriers: dict[str, dict] = {}
        self.store_path = store_path
        self._ship = ship
        self._sync_ship = sync_ship
        self.standby_ok = ship is not None
        self._reserved_until = 0
        self._txid_reserved_until = 0
        if store_path and os.path.exists(store_path):
            with open(store_path) as f:
                st = json.load(f)
            # resume past the reserve window: nothing before it can have
            # been handed out after the crash
            self._ts = st["reserved_ts"]
            self._txid = st["reserved_txid"]
            self._sequences = st.get("sequences", {})
            self._prepared = st.get("prepared", {})
            self._barriers = st.get("barriers", {})
        self._persist_locked()

    def _persist_locked(self):
        st = {"reserved_ts": self._ts + RESERVE,
              "reserved_txid": self._txid + RESERVE,
              "sequences": self._sequences,
              "prepared": self._prepared,
              "barriers": self._barriers}
        if self.store_path:
            tmp = self.store_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(st, f)
            os.replace(tmp, self.store_path)
        if self._ship is not None:
            # ship BEFORE extending the usable window: nothing may be
            # issued from a window the standby hasn't durably seen.
            # Deep-copied: an in-process standby must not alias the live
            # sequence/prepared dicts of a primary that later mutates them
            try:
                # may-acquire: gtm.standby.GtmStandby._lock
                self._ship(json.loads(json.dumps(st)))
                self.standby_ok = True
            except Exception:
                self.standby_ok = False
                if self._sync_ship:
                    raise
        self._reserved_until = self._ts + RESERVE
        self._txid_reserved_until = self._txid + RESERVE

    # ---- catalog generation (multi-coordinator DDL sync): every CN
    # checks this monotone counter before planning and reloads the
    # shared catalog on change (reference: CN-to-CN DDL propagation,
    # EXEC_ON_COORDS fan-out — here the GTM is the sync point).
    # Volatile by design: a GTM restart resets it to 0, which every CN
    # sees as a MISMATCH with its cached value and reloads — safe.
    def catalog_gen(self) -> int:
        with self._lock:
            return getattr(self, "_catalog_gen", 0)

    def bump_catalog_gen(self) -> int:
        with self._lock:
            self._catalog_gen = getattr(self, "_catalog_gen", 0) + 1
            return self._catalog_gen

    # ---- cluster-wide resource queues (reference: gtm_resqueue.c —
    # the GTM is the one place every coordinator already talks to, so
    # per-group concurrency caps enforced here hold across ALL CNs,
    # not per-process).  Each slot records its acquirer identity and a
    # lease deadline: a coordinator that crashes (or loses its GTM
    # connection) between acquire and release can no longer leak the
    # slot forever — expired leases are reaped at the next acquire, and
    # the TCP server reaps a connection's owners on disconnect,
    # mirroring gtm_resqueue.c's per-connection cleanup (ADVICE r5 #3).
    def _resq_slots(self, group: str) -> list:
        # caller holds self._lock; slots: [owner, lease_deadline]
        rq = getattr(self, "_resq", None)
        if rq is None:
            rq = self._resq = {}
        slots = rq.setdefault(group, [])
        now = time.monotonic()
        kept = [s for s in slots if s[1] > now]
        # a reaped lease was an acquire that will never see its release
        # land (the owner crashed or lost its GTM connection): account
        # it, or the acquired/released ledger silently diverges
        if len(kept) != len(slots):
            st = self._resq_stats_dict()
            st["expired"] += len(slots) - len(kept)
        slots[:] = kept
        return slots

    def _resq_stats_dict(self) -> dict:
        # caller holds self._lock
        st = getattr(self, "_resq_stat", None)
        if st is None:
            st = self._resq_stat = {"acquired": 0, "released": 0,
                                    "expired": 0}
        return st

    def resq_acquire(self, group: str, cap: int, owner: str = "",
                     lease_s: float = 30.0) -> bool:
        with self._lock:
            slots = self._resq_slots(group)
            if cap > 0 and len(slots) >= cap:
                return False
            slots.append([owner,
                          time.monotonic() + max(float(lease_s), 0.001)])
            self._resq_stats_dict()["acquired"] += 1
            return True

    def resq_release(self, group: str, owner: str = "") -> None:
        with self._lock:
            slots = self._resq_slots(group)
            for i, s in enumerate(slots):
                if s[0] == owner:
                    del slots[i]
                    self._resq_stats_dict()["released"] += 1
                    return
            # identity-less legacy caller: positional release.  An
            # IDENTIFIED owner whose slot was already lease-reaped must
            # NOT pop someone else's slot — no-op instead (the reap was
            # already counted as `expired`, never double as `released`).
            if slots and not owner:
                del slots[0]
                self._resq_stats_dict()["released"] += 1

    def resq_disconnect(self, owner: str) -> int:
        """Reap every slot held by `owner` (connection closed / session
        gone).  Returns how many were freed."""
        if not owner:
            return 0
        freed = 0
        with self._lock:
            for group in list(getattr(self, "_resq", None) or {}):
                slots = self._resq_slots(group)
                kept = [s for s in slots if s[0] != owner]
                freed += len(slots) - len(kept)
                slots[:] = kept
            if freed:
                # the owner's goodbye IS its release (ledger stays
                # balanced for sessions that die holding slots)
                self._resq_stats_dict()["released"] += freed
        return freed

    def resq_counts(self) -> dict:
        with self._lock:
            return {g: len(self._resq_slots(g))
                    for g in list(getattr(self, "_resq", None) or {})}

    def resq_stats(self) -> dict:
        """Slot-lifecycle ledger: acquired == released + expired +
        (slots currently live) at any quiescent point — the GTM side of
        the scheduler's slot-leak invariant."""
        with self._lock:
            for g in list(getattr(self, "_resq", None) or {}):
                self._resq_slots(g)     # fold pending expiries in
            st = dict(self._resq_stats_dict())
        st["live"] = sum(self.resq_counts().values())
        return st

    # ---- API ----
    def next_gts(self) -> int:
        with self._lock:
            wall = int(time.time() * 1e6)
            self._ts = max(self._ts + 1, wall)
            if self._ts >= self._reserved_until:
                self._persist_locked()
            return self._ts

    def next_txid(self) -> int:
        with self._lock:
            self._txid += 1
            # txid allocation must trigger persistence on its own: a burst
            # of txid-only grants past the reserve window would otherwise
            # let a restarted GTM re-issue txids (advisor r1)
            if self._txid >= self._txid_reserved_until:
                self._persist_locked()
            return self._txid

    def seq_next(self, name: str, cache: int = 1) -> int:
        with self._lock:
            s = self._sequences.setdefault(
                name, {"next": 1, "increment": 1})
            v = s["next"]
            s["next"] = v + s["increment"] * cache
            self._persist_locked()
            return v

    def seq_list(self) -> dict:
        """Live sequence state {name: {"next","increment"}} — dump
        needs positions, not definitions (pg_dump emits setval)."""
        with self._lock:
            return {n: dict(s) for n, s in self._sequences.items()}

    def seq_create(self, name: str, start: int = 1, increment: int = 1):
        with self._lock:
            self._sequences[name] = {"next": start, "increment": increment}
            self._persist_locked()

    def seq_drop(self, name: str):
        with self._lock:
            self._sequences.pop(name, None)
            self._persist_locked()

    # ---- 2PC registry (reference: GTM tracks open/prepared global txns;
    # the in-doubt resolver asks it for verdicts, like pg_clean asks) ----
    def prepare_txn(self, gid: str, participants: list[str], txid: int):
        with self._lock:
            self._prepared[gid] = {"participants": participants,
                                   "txid": txid, "state": "prepared"}
            self._persist_locked()

    def commit_txn(self, gid: str, commit_ts: int):
        with self._lock:
            if gid in self._prepared:
                self._prepared[gid]["state"] = "committed"
                self._prepared[gid]["commit_ts"] = commit_ts
                self._persist_locked()

    def forget_txn(self, gid: str):
        with self._lock:
            self._prepared.pop(gid, None)
            self._persist_locked()

    def abort_txn(self, gid: str):
        with self._lock:
            if gid in self._prepared:
                self._prepared[gid]["state"] = "aborted"
                self._persist_locked()

    def txn_verdict(self, gid: str) -> str:
        """For in-doubt resolution: 'committed' (with ts), 'aborted', or
        'unknown' (never prepared here -> abort is safe)."""
        with self._lock:
            info = self._prepared.get(gid)
            if info is None:
                return "unknown"
            return info["state"]

    def prepared_list(self) -> dict:
        with self._lock:
            return dict(self._prepared)

    # ---- barriers (restore points) ----
    def barrier_create(self, name: str, gts: int):
        with self._lock:
            self._barriers[name] = {"gts": int(gts), "wall": time.time()}
            self._persist_locked()

    def barrier_list(self) -> dict:
        with self._lock:
            return dict(self._barriers)

    def stats(self) -> dict:
        """Read-only observability snapshot (no timestamp allocation)."""
        with self._lock:
            return {"ts": self._ts, "txid": self._txid,
                    "prepared": len(self._prepared)}


class GtmServer:
    """Threaded TCP front end for GtmCore (the reference's thread-pool +
    epoll loop, main.c:4819, collapsed to a threading server — the GTS
    critical section is a single atomic bump either way)."""

    def __init__(self, core: GtmCore, host: str = "127.0.0.1",
                 port: int = 0):
        self.core = core
        core_ref = core

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # owners whose resq slots were acquired over THIS
                # connection: reaped in finish() on disconnect
                # (reference: gtm_resqueue per-connection cleanup)
                self.resq_owners: set = set()
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    if msg is None:
                        return
                    op = msg.get("op")
                    # inbound trace context → handler span; compacted
                    # subtree rides the reply (manual open/close: resp
                    # is assembled across the whole if-chain)
                    sx = xray.server_span(msg, op or "",
                                          node="gtm").open()
                    try:
                        if op == "gts":
                            resp = {"ts": core_ref.next_gts()}
                        elif op == "gts_batch":
                            n = msg.get("n", 1)
                            resp = {"ts": [core_ref.next_gts()
                                           for _ in range(n)]}
                        elif op == "txid":
                            resp = {"txid": core_ref.next_txid()}
                        elif op == "begin":
                            resp = {"txid": core_ref.next_txid(),
                                    "ts": core_ref.next_gts()}
                        elif op == "seq_next":
                            resp = {"v": core_ref.seq_next(
                                msg["name"], msg.get("cache", 1))}
                        elif op == "seq_create":
                            core_ref.seq_create(msg["name"],
                                                msg.get("start", 1),
                                                msg.get("increment", 1))
                            resp = {"ok": True}
                        elif op == "prepare":
                            core_ref.prepare_txn(msg["gid"],
                                                 msg["participants"],
                                                 msg["txid"])
                            resp = {"ok": True}
                        elif op == "commit":
                            core_ref.commit_txn(msg["gid"], msg["ts"])
                            resp = {"ok": True}
                        elif op == "abort":
                            core_ref.abort_txn(msg["gid"])
                            resp = {"ok": True}
                        elif op == "forget":
                            core_ref.forget_txn(msg["gid"])
                            resp = {"ok": True}
                        elif op == "verdict":
                            resp = {"state": core_ref.txn_verdict(
                                msg["gid"])}
                        elif op == "prepared_list":
                            resp = {"prepared": core_ref.prepared_list()}
                        elif op == "barrier_create":
                            core_ref.barrier_create(msg["name"],
                                                    msg["gts"])
                            resp = {"ok": True}
                        elif op == "barrier_list":
                            resp = {"barriers": core_ref.barrier_list()}
                        elif op == "stats":
                            resp = {"stats": core_ref.stats()}
                        elif op == "seq_list":
                            resp = {"seqs": core_ref.seq_list()}
                        elif op == "resq_acquire":
                            owner = msg.get("owner", "")
                            if owner:
                                self.resq_owners.add(owner)
                            # wire passthrough: the release arrives as
                            # its own message; disconnect/lease reap
                            # covers a peer that never sends it
                            resp = {"ok2": core_ref.resq_acquire(  # otblint: disable=slot-discipline
                                msg["group"], msg["cap"], owner,
                                msg.get("lease_s", 30.0))}
                        elif op == "resq_release":
                            core_ref.resq_release(msg["group"],
                                                  msg.get("owner", ""))
                            resp = {"ok": True}
                        elif op == "resq_counts":
                            resp = {"counts": core_ref.resq_counts()}
                        elif op == "resq_disconnect":
                            resp = {"freed": core_ref.resq_disconnect(
                                msg.get("owner", ""))}
                        elif op == "cat_gen":
                            resp = {"gen": core_ref.catalog_gen()}
                        elif op == "cat_gen_bump":
                            resp = {"gen": core_ref.bump_catalog_gen()}
                        elif op == "ping":
                            resp = {"pong": True}
                        else:
                            resp = {"error": f"unknown op {op!r}"}
                    except Exception as e:  # serve errors, don't die
                        resp = {"error": str(e)}
                    sx.close()
                    sx.attach(resp)
                    send_msg(self.request, resp)

            def finish(self):
                for owner in getattr(self, "resq_owners", ()):
                    try:
                        core_ref.resq_disconnect(owner)
                    except Exception:
                        pass
                super().finish()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class GtmClient:
    """Per-backend GTM connection (reference: access/transam/gtm.c
    InitGTM/GetGlobalTimestampGTM)."""

    def __init__(self, host: str, port: int):
        self.addr = (host, port)
        self._sock: Optional[socket.socket] = None
        self._lock = locks.Lock("gtm.server.GtmClient._lock")

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, timeout=10)
        return self._sock

    # the per-client lock IS the wire serializer — one request/response
    # conversation per socket at a time; the hold is bounded by the
    # socket timeout, so the RPC-under-lock here is the design
    def call(self, **msg) -> dict:  # otblint: disable=lock-blocking
        xray.inject(msg)
        op = msg.get("op", "")
        # wait-event attribution: timestamp/slot grants are the two
        # GTM waits tuners actually chase; everything else is generic
        ev = "gts-grant" if op in ("gts", "gts_batch", "begin") \
            else ("gtm-slot" if op == "resq_acquire" else "gtm-rpc")
        with self._lock:
            for attempt in (0, 1):
                try:
                    s = self._conn()
                    # chaos points: tests arm gtm.send/gtm.recv to
                    # simulate GTM loss without killing the server.
                    # wait_event's enter/exit touch the wait register
                    # + histograms (opaque to the callgraph):
                    # may-acquire: obs.xray._WLOCK
                    # may-acquire: obs.metrics.Registry._lock
                    # may-acquire: obs.metrics.metric._lock
                    with xray.wait_event(ev):
                        send_msg(s, msg, fault="gtm.send")
                        # expect_reply: a close while the GTM owes an
                        # answer is a WireError, never "no message"
                        resp = recv_msg(s, expect_reply=True,
                                        fault="gtm.recv")
                    xray.absorb(resp, node="gtm", op=op)
                    if "error" in resp:
                        raise RuntimeError(f"gtm error: {resp['error']}")
                    return resp
                except (ConnectionError, OSError, EOFError):
                    self.close()
                    if attempt:
                        raise
            raise ConnectionError("unreachable")

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    # typed helpers (mirror GtmCore's surface so Cluster can use either)
    def next_gts(self) -> int:
        return self.call(op="gts")["ts"]

    def next_txid(self) -> int:
        return self.call(op="txid")["txid"]

    def begin(self) -> tuple[int, int]:
        r = self.call(op="begin")
        return r["txid"], r["ts"]

    def seq_create(self, name, start=1, increment=1):
        self.call(op="seq_create", name=name, start=start,
                  increment=increment)

    def seq_next(self, name, cache=1) -> int:
        return self.call(op="seq_next", name=name, cache=cache)["v"]

    def prepare_txn(self, gid, participants, txid):
        self.call(op="prepare", gid=gid, participants=participants,
                  txid=txid)

    def commit_txn(self, gid, ts):
        self.call(op="commit", gid=gid, ts=ts)

    def abort_txn(self, gid):
        self.call(op="abort", gid=gid)

    def forget_txn(self, gid):
        self.call(op="forget", gid=gid)

    def txn_verdict(self, gid) -> str:
        return self.call(op="verdict", gid=gid)["state"]

    def prepared_list(self) -> dict:
        return self.call(op="prepared_list")["prepared"]

    def barrier_create(self, name, gts):
        self.call(op="barrier_create", name=name, gts=int(gts))

    def barrier_list(self) -> dict:
        return self.call(op="barrier_list")["barriers"]

    def stats(self) -> dict:
        return self.call(op="stats")["stats"]

    def seq_list(self) -> dict:
        return self.call(op="seq_list")["seqs"]

    def resq_acquire(self, group: str, cap: int, owner: str = "",
                     lease_s: float = 30.0) -> bool:
        return self.call(op="resq_acquire", group=group, cap=cap,
                         owner=owner, lease_s=lease_s)["ok2"]

    def resq_release(self, group: str, owner: str = "") -> None:
        self.call(op="resq_release", group=group, owner=owner)

    def resq_disconnect(self, owner: str) -> int:
        return self.call(op="resq_disconnect", owner=owner)["freed"]

    def resq_counts(self) -> dict:
        return self.call(op="resq_counts")["counts"]

    def catalog_gen(self) -> int:
        return self.call(op="cat_gen")["gen"]

    def bump_catalog_gen(self) -> int:
        return self.call(op="cat_gen_bump")["gen"]
