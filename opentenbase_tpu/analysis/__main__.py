"""``python -m opentenbase_tpu.analysis`` — lint + HLO audit gate.

Runs the four otblint passes and (unless ``--no-hlo``) the StableHLO
kernel audit; exits nonzero when either leaves unsuppressed findings,
so a single command gates CI.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    run_hlo = "--no-hlo" not in argv
    argv = [a for a in argv if a != "--no-hlo"]

    from . import lint
    rc = lint.main(argv)

    if run_hlo and not any(a.startswith("--write-baseline")
                           for a in argv):
        from . import hlo_audit
        rc_hlo = hlo_audit.main(["--kernels-only"])
        rc = rc or rc_hlo
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
