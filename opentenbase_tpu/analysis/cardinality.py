"""otbcard: compile-cardinality & device-residency analysis.

The plancache bounds how many compiled XLA programs stay LIVE; these
passes bound how many can EXIST.  Every compiled-program key component
must have a bounded domain — literal-masked plan structure, quantized
size classes (``size_class``/``next_pow2``/``_batch_class``), pow2
join-ladder factors — because one unbounded component (a raw row
count, wall clock, dict iteration order) turns the LRU into a conveyor
belt: every query compiles, nothing ever hits.  Residency is the dual
constraint: device arrays parked outside the bufferpool are invisible
to ``OTB_DEVICE_CACHE_BYTES`` and to ``shed_coldest``, so the OOM
ladder fires blind.  Four static passes plus a runtime cross-check:

program-cardinality
    Interprocedural dataflow from every ``ProgramCache.put`` site:
    wall-clock / RNG / uuid results, raw ``row_count()`` values not
    passed through a quantizer, and unsorted dict iteration
    (``.items()/.keys()/.values()`` outside ``sorted(...)``) must not
    reach the key expression.  Follows one level into same-project
    callees that feed the key (the ``_table_sig`` shape).

retrace-risk
    Program identity minted per VALUE instead of per CLASS:
    unhashable key components (``ProgramCache.put`` silently skips
    caching on TypeError — every call recompiles), generator/ephemeral
    ``id()`` components (fresh object per call — the key never
    matches), ``int()/float()`` of device data feeding a key, and —
    inside the traced closure — branching that compares a raw
    ``.shape`` int against a non-constant without quantization.

device-residency
    ``jax.device_put`` outside the sanctioned staging layer
    (storage/bufferpool.py, storage/batch.py, parallel/mesh.py, or a
    function that accounts via ``POOL.note_upload``), and
    device-produced values stored into module-level containers outside
    the pool — both are bytes the device budget cannot see.

transfer-discipline
    HostSyncPass (passes.py) proves traced closures sync-free; this
    pass audits the EAGER side of the device-hot trees (exec/,
    storage/, parallel/, ops/): ``jax.device_get`` / ``np.asarray`` of
    device data / ``.tolist()`` / ``.item()`` are findings unless the
    enclosing function is a declared ``# otblint: sync-boundary`` —
    the annotation enumerates every legal materialization point in the
    engine, greppably.

retrace-witness
    Cross-check of ``analysis/program_census.json`` — per-program
    compile provenance recorded by the OTB_TRACECHECK=1 sanitizer in
    exec/plancache.py — against the static ladder predictions: every
    witnessed class int must be ladder-shaped (pow2 or the
    quarter-step {4,5,6,7}*2^k classes — at most 3 significant bits),
    join factors must respect the 4096 ladder cap, a key re-put
    without an eviction is an unexplained retrace, and a fragment
    fanning out past ``_STORM_LIMIT`` class combinations is a compile
    storm.  The same witness pattern as analysis/concurrency.py's
    lock_order.json: runtime reality may never exceed what the static
    model predicts.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Optional

from .callgraph import TracedClosure, is_traced_guard_test
from .core import Finding, FuncInfo, Project
from .passes import ProgramKeyPass, _dotted, _Emitter, _fn_disabled

#: functions that collapse an unbounded int into a bounded class
_QUANT_FUNCS = frozenset({"size_class", "next_pow2", "_batch_class",
                          "chunk_class", "lut_capacity", "codec_class",
                          "codec_classes"})
#: identifier tokens that smell like a raw encoding descriptor — an
#: Enc's reference / LUT contents drift with appends, so only the
#: quantized codec-class token (codec_class/codec_classes) may reach
#: program-key material (storage/codec.py)
_ENC_TOKENS = frozenset({"enc", "encs", "encm", "encoding", "encodings",
                         "codec", "codecs"})
#: call prefixes whose results have an unbounded / per-process domain
_UNBOUNDED_PREFIXES = ("time.", "datetime.", "random.", "secrets.",
                       "uuid.", "numpy.random.")
_UNBOUNDED_CALLS = frozenset({"os.getpid", "os.urandom",
                              "threading.get_ident"})
#: list-producing calls — unhashable as a direct key component
_LIST_CALLS = frozenset({"sorted", "list"})
#: calls that return hashable scalars/containers — safe key components
_HASHABLE_CALLS = frozenset({"tuple", "frozenset", "struct_key",
                             "fingerprint", "hash", "id", "int", "str",
                             "float", "bool", "len", "min", "max",
                             "sum", "repr", "next_pow2", "size_class",
                             "_batch_class", "chunk_class", "getattr",
                             "lut_capacity", "codec_class",
                             "codec_classes"})
#: constructors of fresh per-call objects — id() of one is ephemeral
_FRESH_CALLS = frozenset({"dict", "list", "set", "object", "bytearray"})

_STORM_LIMIT = 64      # class combinations per fragment signature
_FACTOR_CAP = 4096     # exec/fused.py / mesh_exec.py ladder exhaustion


def _loads(e) -> set:
    return {n.id for n in ast.walk(e)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _assign_exprs(fn_node) -> dict:
    """name -> list of (RHS expression, via_iter) from every binding
    form (the expression-level sibling of
    ProgramKeyPass._assignments).  ``via_iter`` marks loop/
    comprehension-target bindings: the bound name holds one ELEMENT of
    the iterable, so iteration-ORDER concerns do not transfer through
    it (the comprehension expression itself is walked in its real
    sorted(...) context)."""
    out: dict = {}

    def bind(t, value, via_iter=False):
        if isinstance(t, ast.Name):
            out.setdefault(t.id, []).append((value, via_iter))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for x in t.elts:
                bind(x, value, via_iter)
        elif isinstance(t, ast.Starred):
            bind(t.value, value, via_iter)
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            root = t
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if isinstance(root, ast.Name):
                out.setdefault(root.id, []).append((value, via_iter))

    for st in ast.walk(fn_node):
        if isinstance(st, ast.Assign):
            for t in st.targets:
                bind(t, st.value)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)) and \
                getattr(st, "value", None) is not None:
            bind(st.target, st.value)
        elif isinstance(st, ast.For):
            bind(st.target, st.iter, via_iter=True)
        elif isinstance(st, ast.NamedExpr):
            bind(st.target, st.value)
        elif isinstance(st, ast.withitem) and st.optional_vars:
            bind(st.optional_vars, st.context_expr)
        elif isinstance(st, ast.comprehension):
            bind(st.target, st.iter, via_iter=True)
    return out


def _flow_exprs(fi: FuncInfo, seed_expr) -> list:
    """[(expr, via_iter)] — the seed expression plus the RHS of every
    assignment that (transitively) feeds a name appearing in it: the
    set of expressions whose values can reach the seed."""
    assigns = _assign_exprs(fi.node)
    exprs = [(seed_expr, False)]
    seen_ids = {id(seed_expr)}
    names = _loads(seed_expr)
    frontier = list(names)
    while frontier:
        nm = frontier.pop()
        for rhs, via_iter in assigns.get(nm, ()):
            if id(rhs) in seen_ids:
                continue
            seen_ids.add(id(rhs))
            exprs.append((rhs, via_iter))
            for n2 in _loads(rhs):
                if n2 not in names:
                    names.add(n2)
                    frontier.append(n2)
    return exprs


def _return_exprs(fi: FuncInfo) -> list:
    return [st.value for st in ast.walk(fi.node)
            if isinstance(st, ast.Return) and st.value is not None]


def _producer_call(e, mi, pkg: str) -> bool:
    """Whether the expression subtree contains a device-data producer
    (a jax/jnp/kernels call)."""
    for n in ast.walk(e):
        if isinstance(n, ast.Call):
            d = _dotted(n.func, mi) or ""
            if d.startswith("jax.") or d == "jax" or \
                    d.startswith(f"{pkg}.ops.kernels."):
                return True
    return False


# ===========================================================================
# program-cardinality
# ===========================================================================
class ProgramCardinalityPass:
    """Every ``ProgramCache.put`` key component must have a bounded
    domain.  Positive-evidence detection only (the repo convention:
    prefer missing a case over crying wolf) — a finding names the
    unbounded source it actually saw in the key's dataflow."""

    rule = "program-cardinality"

    def __init__(self, project: Project,
                 closure: Optional[TracedClosure] = None):
        self.project = project
        self._pk = ProgramKeyPass(project)
        self.closure = closure

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            for fi in mi.functions.values():
                for call in ast.walk(fi.node):
                    if isinstance(call, ast.Call) and \
                            self._pk._is_cache_put(call):
                        self._check_put(mi, fi, call, em)
        return em.findings

    def _callee(self, mi, fi: FuncInfo, call) -> Optional[FuncInfo]:
        """Same-project callee of a Call in key flow (one level)."""
        f = call.func
        if isinstance(f, ast.Name):
            tgt = mi.functions.get(f"{fi.qualname}.{f.id}") \
                or mi.functions.get(f.id)
            if tgt is None and fi.class_name:
                tgt = mi.functions.get(f"{fi.class_name}.{f.id}")
            if tgt is None and f.id in mi.import_symbols:
                dmod, attr = mi.import_symbols[f.id]
                tgt = self.project.function(dmod, attr)
            return tgt
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            if f.value.id in ("self", "cls") and fi.class_name:
                return mi.functions.get(f"{fi.class_name}.{f.attr}")
            dmod = mi.import_modules.get(f.value.id)
            if dmod is not None:
                return self.project.function(dmod, f.attr)
        return None

    def _check_put(self, mi, fi: FuncInfo, call, em: _Emitter):
        key_expr = call.args[0]
        sites = [(e, it, fi, mi) for e, it in _flow_exprs(fi, key_expr)]
        # one level into same-project callees feeding the key
        # (_table_sig's id()/dict-iteration must be visible here)
        seen_fns = {(fi.module, fi.qualname)}
        for e, _it, _fi, _mi in list(sites):
            for n in ast.walk(e):
                if not isinstance(n, ast.Call):
                    continue
                tgt = self._callee(_mi, _fi, n)
                if tgt is None or (tgt.module, tgt.qualname) in seen_fns:
                    continue
                if tgt.qualname.split(".")[-1] in _QUANT_FUNCS:
                    # a quantizer's INTERNALS aren't key material — its
                    # whole point is collapsing the raw domain before
                    # the key sees it
                    continue
                seen_fns.add((tgt.module, tgt.qualname))
                tmi = self.project.modules[tgt.module]
                for ret in _return_exprs(tgt):
                    sites.extend((x, it, tgt, tmi)
                                 for x, it in _flow_exprs(tgt, ret))
        for e, via_iter, efi, emi in sites:
            self._scan(e, via_iter, efi, emi, em)

    def _scan(self, expr, via_iter: bool, fi: FuncInfo, mi,
              em: _Emitter):
        def walk(e, in_sorted: bool, in_quant: bool):
            if isinstance(e, ast.Call):
                d = _dotted(e.func, mi) or ""
                short = d.split(".")[-1]
                if short == "sorted":
                    for c in ast.iter_child_nodes(e):
                        if isinstance(c, ast.expr):
                            walk(c, True, in_quant)
                        elif isinstance(c, ast.comprehension):
                            walk(c.iter, True, in_quant)
                    return
                if short in _QUANT_FUNCS:
                    for c in ast.iter_child_nodes(e):
                        if isinstance(c, ast.expr):
                            walk(c, in_sorted, True)
                    return
                if d.startswith(_UNBOUNDED_PREFIXES) or \
                        d in _UNBOUNDED_CALLS:
                    em.emit(fi, e.lineno,
                            f"{d}() in program-key material — wall "
                            f"clock / RNG / process identity has an "
                            f"unbounded domain, so every call mints a "
                            f"fresh compiled program")
                elif short == "row_count" and not in_quant:
                    em.emit(fi, e.lineno,
                            "raw row count in program-key material — "
                            "quantize through size_class()/next_pow2() "
                            "so the compile population stays a ladder, "
                            "not one program per table size")
                elif isinstance(e.func, ast.Attribute) and \
                        e.func.attr in ("items", "keys", "values") and \
                        not e.args and not in_sorted:
                    em.emit(fi, e.lineno,
                            f".{e.func.attr}() iteration order in "
                            f"program-key material — wrap in "
                            f"sorted(...) or two processes with "
                            f"different insertion orders compile "
                            f"distinct programs for one fragment")
            elif isinstance(e, ast.Name) and \
                    isinstance(e.ctx, ast.Load) and not in_quant and \
                    "chunk" in e.id.lower():
                em.emit(fi, e.lineno,
                        f"raw chunk count/size '{e.id}' in program-key "
                        f"material — a morsel stream re-sizes its "
                        f"window under pressure, so quantize through "
                        f"chunk_class() or one stream mints one "
                        f"compiled program per chunk geometry")
                return
            elif isinstance(e, ast.Name) and \
                    isinstance(e.ctx, ast.Load) and not in_quant and \
                    any(t in _ENC_TOKENS
                        for t in e.id.lower().split("_")):
                em.emit(fi, e.lineno,
                        f"raw encoding descriptor '{e.id}' in "
                        f"program-key material — FOR references and "
                        f"dictionary LUTs drift with appends, so key "
                        f"on the quantized codec-class token "
                        f"(codec_class()/codec_classes(); LUT shapes "
                        f"through lut_capacity()) or every descriptor "
                        f"drift mints a fresh compiled program")
                return
            for c in ast.iter_child_nodes(e):
                if isinstance(e, ast.Call) and c is e.func and \
                        isinstance(c, ast.Name):
                    continue   # callee name, not key material
                if isinstance(c, ast.expr):
                    walk(c, in_sorted, in_quant)
                elif isinstance(c, ast.comprehension):
                    walk(c.iter, in_sorted, in_quant)
                    for cond in c.ifs:
                        walk(cond, in_sorted, in_quant)

        # iter-bound flow: the name holds an ELEMENT, so iteration
        # order of the RHS does not transfer — start in sorted context
        walk(expr, via_iter, False)


# ===========================================================================
# result-key
# ===========================================================================
class ResultKeyPass:
    """Result-cache key discipline (exec/share.py, the GTS-versioned
    result cache).  An entry is servable to ANY later snapshot that
    covers its GTS, so every ``ResultCache.put`` key component must
    derive from the literal-masked signature, the literal vector, or
    the store-version/GTS tuple — the three inputs that exactly
    determine the result.  Positive-evidence detection (the repo
    convention): wall-clock / RNG / process-identity reads in the key
    flow defeat reuse (every put mints a fresh never-matching entry),
    and a raw row count keys the entry on what the result LOOKED like
    instead of what produced it — a post-DML table at the same
    cardinality would wrongly match."""

    rule = "result-key"

    def __init__(self, project: Project):
        self.project = project
        # every module-level name bound to a ResultCache() anywhere
        # (the ProgramKeyPass receiver convention)
        self.cache_names: set = set()
        for mi in project.modules.values():
            for st in mi.src.tree.body:
                if isinstance(st, ast.Assign) and \
                        isinstance(st.value, ast.Call):
                    f = st.value.func
                    nm = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else None)
                    if nm == "ResultCache":
                        for t in st.targets:
                            if isinstance(t, ast.Name):
                                self.cache_names.add(t.id)

    def _is_cache_put(self, call) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "put"
                and len(call.args) >= 2):
            return False
        owner = f.value
        name = owner.id if isinstance(owner, ast.Name) else (
            owner.attr if isinstance(owner, ast.Attribute) else None)
        return name in self.cache_names

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            for fi in mi.functions.values():
                for call in ast.walk(fi.node):
                    if isinstance(call, ast.Call) and \
                            self._is_cache_put(call):
                        self._check_put(mi, fi, call, em)
        return em.findings

    # one level into same-project callees feeding the key — the
    # resolution rules are ProgramCardinalityPass's, shared verbatim
    _callee = ProgramCardinalityPass._callee

    #: tokens that mark a value as coming from the producing snapshot
    _SNAP_TOKENS = frozenset({"snap", "snapshot", "gts", "snapshot_ts",
                              "snapshot_gts", "next_gts"})

    def _check_gts_tag(self, fi: FuncInfo, call, em: _Emitter):
        """The put's GTS tag (2nd positional arg) bounds which future
        snapshots the entry may serve — it must flow from the snapshot
        the result was PRODUCED under (``item.snap`` /
        ``gts.next_gts()``), not from a constant or an unrelated
        counter: a fabricated tag lets ``lookup``'s
        ``snapshot_gts >= tag`` gate hand tomorrow's rows to
        yesterday's snapshot."""
        toks: set = set()
        for e, _it in _flow_exprs(fi, call.args[1]):
            for n in ast.walk(e):
                if isinstance(n, ast.Name):
                    toks.add(n.id)
                elif isinstance(n, ast.Attribute):
                    toks.add(n.attr)
        if not toks & self._SNAP_TOKENS:
            em.emit(fi, call.lineno,
                    "result-cache GTS tag does not flow from the "
                    "producing snapshot (no snap/gts/next_gts "
                    "material in its flow) — a fabricated tag defeats "
                    "the lookup staleness gate")

    def _check_put(self, mi, fi: FuncInfo, call, em: _Emitter):
        self._check_gts_tag(fi, call, em)
        key_expr = call.args[0]
        sites = [(e, fi, mi) for e, _it in _flow_exprs(fi, key_expr)]
        seen_fns = {(fi.module, fi.qualname)}
        for e, _fi, _mi in list(sites):
            for n in ast.walk(e):
                if not isinstance(n, ast.Call):
                    continue
                tgt = self._callee(_mi, _fi, n)
                if tgt is None or (tgt.module, tgt.qualname) in seen_fns:
                    continue
                seen_fns.add((tgt.module, tgt.qualname))
                tmi = self.project.modules[tgt.module]
                for ret in _return_exprs(tgt):
                    sites.extend((x, tgt, tmi)
                                 for x, _it in _flow_exprs(tgt, ret))
        for e, efi, emi in sites:
            self._scan(e, efi, emi, em)

    def _scan(self, expr, fi: FuncInfo, mi, em: _Emitter):
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func, mi) or ""
            short = d.split(".")[-1]
            if d.startswith(_UNBOUNDED_PREFIXES) or \
                    d in _UNBOUNDED_CALLS:
                em.emit(fi, n.lineno,
                        f"{d}() in result-cache key material — wall "
                        f"clock / RNG / process identity never "
                        f"repeats, so every put mints an entry no "
                        f"lookup can match; key on the masked "
                        f"signature, literal vector, and "
                        f"store-version/GTS tuple only")
            elif short == "row_count":
                em.emit(fi, n.lineno,
                        "raw row count in result-cache key material — "
                        "it keys the entry on what the result looked "
                        "like, not what produced it: a post-DML table "
                        "at the same cardinality would wrongly match; "
                        "use the store-version tuple for exact "
                        "invalidation instead")
            elif short == "len" and n.args and any(
                    isinstance(x, ast.Name) and "row" in x.id.lower()
                    for x in ast.walk(n.args[0])):
                em.emit(fi, n.lineno,
                        "raw result size in result-cache key material "
                        "— len(rows) is a property of the answer, not "
                        "of the (signature, literals, store-version) "
                        "inputs that determine it; drop it from the "
                        "key")


# ===========================================================================
# retrace-risk
# ===========================================================================
class RetraceRiskPass:
    """Per-value program identity: the program still caches, but the
    key (or the jit signature) can never repeat — functionally a
    compile per call."""

    rule = "retrace-risk"

    def __init__(self, project: Project, closure: TracedClosure):
        self.project = project
        self.closure = closure
        self._pk = ProgramKeyPass(project)

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            for fi in mi.functions.values():
                for call in ast.walk(fi.node):
                    if isinstance(call, ast.Call) and \
                            self._pk._is_cache_put(call):
                        self._check_put(mi, fi, call, em)
        for fi in self.closure.functions():
            self._check_traced(fi, em)
        return em.findings

    # -- put-site checks ------------------------------------------------
    def _check_put(self, mi, fi: FuncInfo, call, em: _Emitter):
        assigns = _assign_exprs(fi.node)
        self._hashable(call.args[0], fi, mi, assigns, em, set())
        for e, _via_iter in _flow_exprs(fi, call.args[0]):
            self._scan_flow(e, fi, mi, assigns, em)

    def _hashable(self, e, fi, mi, assigns, em: _Emitter,
                  stack: set) -> None:
        """Flag key components that make ``put`` silently not cache
        (TypeError) or never match (fresh object identity)."""
        if isinstance(e, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            em.emit(fi, e.lineno,
                    "unhashable program-key component — "
                    "ProgramCache.put swallows the TypeError and "
                    "skips caching, so every call recompiles; wrap "
                    "in tuple(...)")
            return
        if isinstance(e, ast.GeneratorExp):
            em.emit(fi, e.lineno,
                    "generator object as a program-key component — "
                    "hashable by identity, fresh per call, the key "
                    "never matches; materialize with tuple(...)")
            return
        if isinstance(e, ast.Tuple):
            for x in e.elts:
                self._hashable(x, fi, mi, assigns, em, stack)
            return
        if isinstance(e, ast.BinOp):
            self._hashable(e.left, fi, mi, assigns, em, stack)
            self._hashable(e.right, fi, mi, assigns, em, stack)
            return
        if isinstance(e, ast.Call):
            d = _dotted(e.func, mi) or ""
            short = d.split(".")[-1]
            if short in _LIST_CALLS:
                em.emit(fi, e.lineno,
                        f"{short}(...) is a list — unhashable as a "
                        f"program-key component; wrap in tuple(...)")
            return  # other calls: unknown return, assume hashable
        if isinstance(e, ast.Name) and e.id not in stack:
            for rhs, via_iter in assigns.get(e.id, ()):
                if via_iter:
                    continue   # element of an iterable, not the list
                self._hashable(rhs, fi, mi, assigns, em,
                               stack | {e.id})

    def _scan_flow(self, e, fi, mi, assigns, em: _Emitter):
        pkg = self.project.package
        for n in ast.walk(e):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func, mi) or ""
            short = d.split(".")[-1]
            if short == "id" and len(n.args) == 1 and \
                    isinstance(n.args[0], ast.Name):
                for rhs, via_iter in assigns.get(n.args[0].id, ()):
                    if via_iter:
                        continue   # id() of an element, not the list
                    fresh = isinstance(rhs, (ast.List, ast.Dict,
                                             ast.Set, ast.ListComp,
                                             ast.DictComp, ast.SetComp,
                                             ast.GeneratorExp)) or (
                        isinstance(rhs, ast.Call)
                        and (_dotted(rhs.func, mi) or ""
                             ).split(".")[-1] in _FRESH_CALLS)
                    if fresh:
                        em.emit(fi, n.lineno,
                                f"id() of the ephemeral local "
                                f"'{n.args[0].id}' in program-key "
                                f"material — a fresh object per call "
                                f"means the key never repeats")
                        break
            elif short in ("int", "float") and n.args and \
                    _producer_call(n.args[0], mi, pkg):
                em.emit(fi, n.lineno,
                        f"{short}() of a device value in program-key "
                        f"material — a per-value host read minting "
                        f"one compiled program per datum; quantize "
                        f"the value or mask it as a traced input")

    # -- traced-closure checks ------------------------------------------
    def _check_traced(self, fi: FuncInfo, em: _Emitter):
        mi = self.project.modules[fi.module]

        def shape_side(e) -> bool:
            for n in ast.walk(e):
                if isinstance(n, ast.Attribute) and n.attr == "shape":
                    return True
            return False

        def quantized(e) -> bool:
            for n in ast.walk(e):
                if isinstance(n, ast.Call):
                    d = (_dotted(n.func, mi) or "").split(".")[-1]
                    if d in _QUANT_FUNCS:
                        return True
            return False

        def const_side(e) -> bool:
            return all(isinstance(n, (ast.Constant, ast.UnaryOp,
                                      ast.BinOp, ast.operator,
                                      ast.unaryop, ast.expr_context))
                       for n in ast.walk(e))

        def check_test(test):
            if isinstance(test, ast.BoolOp):
                for v in test.values:
                    check_test(v)
                return
            if not isinstance(test, ast.Compare) or quantized(test):
                return
            sides = [test.left] + list(test.comparators)
            shapes = [s for s in sides if shape_side(s)]
            others = [s for s in sides if not shape_side(s)]
            if shapes and others and \
                    not all(const_side(o) for o in others):
                em.emit(fi, test.lineno,
                        "traced-code branch compares a raw .shape int "
                        "against a runtime value — program structure "
                        "specializes per value; quantize through "
                        "size_class()/next_pow2() first")

        for st in ast.walk(fi.node):
            if isinstance(st, (ast.If, ast.While)):
                if is_traced_guard_test(st.test) is None:
                    check_test(st.test)
            elif isinstance(st, ast.IfExp):
                if is_traced_guard_test(st.test) is None:
                    check_test(st.test)


# ===========================================================================
# device-residency
# ===========================================================================
class DeviceResidencyPass:
    """Device bytes must be visible to the budget.  Uploads happen in
    the staging layer (which accounts them via ``POOL.note_upload``);
    anything else parking device arrays — a stray ``jax.device_put``,
    a module-global holding kernel outputs — is residency the OOM
    ladder cannot evict."""

    rule = "device-residency"

    def __init__(self, project: Project):
        self.project = project
        pkg = project.package
        self.sanctioned_files = (f"{pkg}/storage/bufferpool.py",
                                 f"{pkg}/storage/batch.py",
                                 f"{pkg}/parallel/mesh.py")

    def _accounts(self, fi: FuncInfo) -> bool:
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Call):
                f = n.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if name == "note_upload":
                    return True
        return False

    def run(self) -> list:
        em = _Emitter(self.rule)
        for rel, mi in self.project.by_rel.items():
            norm = rel.replace(os.sep, "/")
            if norm in self.sanctioned_files:
                continue
            # cheap text pre-filter: only parse-walk modules that can
            # possibly trip either check
            has_put = "device_put" in mi.src.text
            if not has_put and not mi.containers:
                continue
            if has_put:
                for fi in mi.functions.values():
                    if self._accounts(fi):
                        continue
                    self._check_fn(mi, fi, em)
            if mi.containers:
                self._check_globals(mi, em)
        return em.findings

    def _check_fn(self, mi, fi: FuncInfo, em: _Emitter):
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Call):
                d = _dotted(n.func, mi) or ""
                if d == "jax.device_put":
                    em.emit(fi, n.lineno,
                            "jax.device_put outside the bufferpool "
                            "staging layer — these bytes are invisible "
                            "to OTB_DEVICE_CACHE_BYTES and to "
                            "shed_coldest; stage through the pool")

    def _check_globals(self, mi, em: _Emitter):
        """Device-produced values stored into module-level containers:
        long-lived residency with no pool accounting."""
        pkg = self.project.package
        for fi in mi.functions.values():
            for st in ast.walk(fi.node):
                target = None
                value = None
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Subscript):
                    target, value = st.targets[0].value, st.value
                elif isinstance(st, ast.Call) and \
                        isinstance(st.func, ast.Attribute) and \
                        st.func.attr in ("append", "add", "update",
                                         "setdefault", "insert"):
                    target = st.func.value
                    value = ast.Tuple(elts=list(st.args), ctx=None) \
                        if st.args else None
                if not isinstance(target, ast.Name) or value is None:
                    continue
                if target.id not in mi.containers:
                    continue
                if _producer_call(value, mi, pkg):
                    em.emit(fi, st.lineno,
                            f"device-produced value stored into "
                            f"module-level '{target.id}' — untracked "
                            f"device residency outside the bufferpool "
                            f"(OTB_DEVICE_CACHE_BYTES cannot see it)")


# ===========================================================================
# transfer-discipline
# ===========================================================================
class TransferDisciplinePass:
    """Host pulls in EAGER engine code (HostSyncPass owns the traced
    closure).  Every ``jax.device_get``, ``np.asarray``-of-device-data,
    ``.tolist()``, ``.item()`` in the device-hot trees must sit inside
    a function declared ``# otblint: sync-boundary`` — the complete,
    greppable inventory of where the engine is allowed to wait on the
    device."""

    rule = "transfer-discipline"

    def __init__(self, project: Project, closure: TracedClosure):
        self.project = project
        self.closure = closure
        pkg = project.package
        self.scope = (f"{pkg}/exec/", f"{pkg}/storage/",
                      f"{pkg}/parallel/", f"{pkg}/ops/")

    _SINK_TEXT = ("device_get", "asarray", "block_until_ready",
                  ".tolist", ".item")

    def run(self) -> list:
        em = _Emitter(self.rule)
        for rel, mi in self.project.by_rel.items():
            if not rel.replace(os.sep, "/").startswith(self.scope):
                continue
            # cheap text pre-filter: a module with no sink spelling
            # anywhere cannot produce a finding
            if not any(s in mi.src.text for s in self._SINK_TEXT):
                continue
            for fi in mi.functions.values():
                if (fi.module, fi.qualname) in self.closure.reachable:
                    continue   # HostSyncPass territory
                if fi.sync_boundary or _fn_disabled(fi, self.rule):
                    continue
                self._check_fn(mi, fi, em)
        return em.findings

    def _check_fn(self, mi, fi: FuncInfo, em: _Emitter):
        pkg = self.project.package
        tainted: set = set()

        def is_producer(call) -> bool:
            d = _dotted(call.func, mi) or ""
            if d in ("jax.devices", "jax.local_devices",
                     "jax.device_count"):
                return False   # device HANDLES, not device data
            return (d.startswith("jax.") or d == "jax"
                    or d.startswith(f"{pkg}.ops.kernels."))

        def taint(e) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Attribute):
                return False   # attr reads: unknown provenance
            if isinstance(e, ast.Subscript):
                return taint(e.value)
            if isinstance(e, ast.Call):
                if is_producer(e):
                    return True
                return any(taint(x) for x in e.args)
            if isinstance(e, (ast.BinOp,)):
                return taint(e.left) or taint(e.right)
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                return any(taint(x) for x in e.elts)
            if isinstance(e, ast.IfExp):
                return taint(e.body) or taint(e.orelse)
            return False

        def note_assign(st):
            v = st.value if hasattr(st, "value") else None
            if v is None:
                return
            is_t = taint(v)
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    if is_t:
                        tainted.add(t.id)
                    else:
                        tainted.discard(t.id)

        def check_call(n):
            d = _dotted(n.func, mi) or ""
            short = d.split(".")[-1]
            if d == "jax.device_get":
                em.emit(fi, n.lineno,
                        "jax.device_get in eager engine code outside "
                        "a declared sync boundary — mark the function "
                        "'# otblint: sync-boundary' if this is a "
                        "sanctioned materialization point")
            elif d.startswith("numpy.") and \
                    short in ("asarray", "array", "copy") and n.args:
                a0 = n.args[0]
                direct_get = isinstance(a0, ast.Call) and \
                    (_dotted(a0.func, mi) or "") == "jax.device_get"
                if taint(a0) and not direct_get:
                    em.emit(fi, n.lineno,
                            f"np.{short}() pulls device data to the "
                            f"host outside a declared sync boundary")
            elif isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("tolist", "item") and \
                    taint(n.func.value):
                em.emit(fi, n.lineno,
                        f".{n.func.attr}() pulls device data to the "
                        f"host outside a declared sync boundary")

        # two passes over the body: taint fixpoint, then sinks — cheap
        # and order-insensitive for the straight-line staging helpers
        # this pass audits
        for _ in range(2):
            for st in ast.walk(fi.node):
                if isinstance(st, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign)):
                    note_assign(st)
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Call):
                check_call(n)


# ===========================================================================
# retrace-witness
# ===========================================================================
def is_ladder_int(v) -> bool:
    """True when v is a legal size/factor class: pow2 (join factors,
    batch classes, exchange multipliers) or quarter-step
    {4,5,6,7}*2^k (staged-table size classes) — equivalently, at most
    3 significant bits."""
    if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
        return False
    bl = v.bit_length()
    if bl <= 3:
        return True
    return (v >> (bl - 3)) << (bl - 3) == v


_CODEC_FAMS = frozenset({"pack8", "pack16", "pack32",
                         "for8", "for16", "for32"})


def _codec_class_ok(tok) -> bool:
    """A witnessed codec class must be one of the quantized tokens
    storage/codec.py codec_class() can mint — raw, a family+width from
    the fixed enum, or dictN with a pow2 LUT capacity.  Anything else
    in a "codec:" census dimension means a raw encoding descriptor
    leaked into a program key."""
    if not isinstance(tok, str):
        return False
    if tok == "raw" or tok in _CODEC_FAMS:
        return True
    if tok.startswith("dict"):
        base, _, cap = tok.partition("/")
        if base not in ("dict8", "dict16") or not cap.isdigit():
            return False
        c = int(cap)
        return c >= 16 and (c & (c - 1)) == 0
    return False


def check_census(data) -> list:
    """Validate a program-census dict against the static ladder
    predictions; returns human-readable violation strings.  Shared by
    RetraceWitnessPass and the tier-1 witness test."""
    out: list = []
    entries = data.get("entries", [])
    if not isinstance(entries, list):
        return ["malformed census: 'entries' is not a list"]
    combos: dict = {}
    for ent in entries:
        if not isinstance(ent, dict):
            out.append(f"malformed census entry: {ent!r}")
            continue
        tier = ent.get("tier", "?")
        kfp = ent.get("key", "?")
        for cls in ent.get("classes", []):
            if not (isinstance(cls, (list, tuple)) and len(cls) == 2):
                out.append(f"{tier}/{kfp}: malformed class {cls!r}")
                continue
            dim, v = cls
            if str(dim).startswith("codec:"):
                if not _codec_class_ok(v):
                    out.append(
                        f"{tier}/{kfp}: witnessed codec class {v!r} "
                        f"for {dim} is not a quantized codec-class "
                        f"token — a raw encoding descriptor (FOR "
                        f"reference / dict LUT) reached a program key")
            elif not is_ladder_int(v):
                out.append(
                    f"{tier}/{kfp}: witnessed {dim} class {v!r} is "
                    f"not ladder-shaped (pow2 or quarter-step) — an "
                    f"unquantized value reached a program key")
            elif str(dim).startswith("factor") and v > _FACTOR_CAP:
                out.append(
                    f"{tier}/{kfp}: witnessed join factor {v} exceeds "
                    f"the {_FACTOR_CAP} ladder cap — the exhaustion "
                    f"fallback did not fire")
        puts = ent.get("puts", 1)
        if isinstance(puts, int) and puts > 1:
            out.append(
                f"{tier}/{kfp}: program signature compiled {puts} "
                f"times without an eviction — an unexplained retrace")
        frag = ent.get("frag")
        if frag is not None:
            combos[(tier, frag)] = combos.get((tier, frag), 0) + 1
    for (tier, frag), n in sorted(combos.items()):
        if n > _STORM_LIMIT:
            out.append(
                f"{tier}/{frag}: {n} class combinations for one "
                f"fragment signature (> {_STORM_LIMIT}) — compile "
                f"storm")
    return out


class RetraceWitnessPass:
    """Cross-check the runtime program census (OTB_TRACECHECK=1,
    exec/plancache.py) against the static ladder predictions."""

    rule = "retrace-witness"

    def __init__(self, project: Project):
        self.project = project

    def run(self) -> list:
        path = os.path.join(self.project.root, self.project.package,
                            "analysis", "program_census.json")
        if not os.path.exists(path):
            return []
        rel = os.path.relpath(path, self.project.root).replace(
            os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            return [Finding(self.rule, rel, 1, "",
                            f"unreadable program census: {e}")]
        return [Finding(self.rule, rel, 1, "", msg)
                for msg in check_census(data)]
