"""Traced-region call graph: which functions can run under a trace.

Roots are discovered, not configured:

- any local function passed to ``jax.jit`` / ``jit`` / ``shard_map``
  (exec/fused.py ``_build_program.run``, exec/mesh_exec.py
  ``_execute.prog``);
- every public top-level function of ``ops.kernels`` (the jit-inlined
  kernel library — each is traced whenever an engine program uses it).

Edges are name-resolved over the package's ASTs:

- plain calls to same-module or imported functions;
- ``mod.fn(...)`` through import aliases;
- ``self.m(...)`` to the enclosing class (plus same-module classes);
- ``obj.m(...)`` to any scanned class method named ``m`` when the name
  is distinctive (a blocklist keeps ``get``/``put``/``items``/... from
  wiring the closure to the whole repo);
- the executor's ``getattr(self, f"_exec_{...}")`` dispatch expands to
  every same-class method matching the literal prefix.

Calls inside an EAGER region — an ``if not self._traced:`` branch, the
``else`` of ``if self._traced:``, or the else-arm of a ``_traced``
ternary — do not create edges: that is the engine's sanctioned
traced/eager split (exec/executor.py).  Functions marked
``# otblint: eager-only`` are asserted host-side and stop the walk.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import FuncInfo, Project

#: method names too generic to resolve across classes by name alone
GENERIC_NAMES = frozenset({
    "get", "put", "pop", "push", "add", "items", "keys", "values",
    "append", "extend", "update", "clear", "sort", "sorted", "copy",
    "setdefault", "remove", "discard", "insert", "index", "count",
    "join", "split", "rsplit", "strip", "lstrip", "rstrip", "replace",
    "startswith", "endswith", "format", "encode", "decode", "lower",
    "upper", "title", "find", "rfind", "search", "match", "fullmatch",
    "group", "groups", "findall", "finditer", "sub", "read", "write",
    "close", "flush", "send", "recv", "sendall", "connect", "bind",
    "listen", "accept", "acquire", "release", "wait", "notify", "set",
    "is_set", "start", "run", "cancel", "result", "done", "next",
    "item", "tolist", "astype", "reshape", "sum", "min", "max", "mean",
    "any", "all", "exists", "mkdir", "open",
})

_JIT_NAMES = {"jit", "shard_map", "pjit", "checkpoint", "remat"}


def _call_name(func) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def is_traced_guard_test(test) -> Optional[str]:
    """Classify an ``if`` test against the engine's _traced idiom:
    returns "traced" when the true-branch is the traced side, "eager"
    when the true-branch is the eager side, None when unrelated.  A
    conjunction counts if any conjunct does."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            r = is_traced_guard_test(v)
            if r is not None:
                return r
        return None
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = is_traced_guard_test(test.operand)
        if inner == "traced":
            return "eager"
        if inner == "eager":
            return "traced"
        return None
    if isinstance(test, ast.Attribute) and test.attr == "_traced":
        return "traced"
    if isinstance(test, ast.Name) and test.id == "_traced":
        return "traced"
    return None


class _GuardedWalker:
    """Shared statement walker that tracks whether the current position
    is inside an eager-only region of a function body.  Subclass hooks:
    ``on_call``, ``on_stmt``, ``on_expr`` (all optional)."""

    def walk_function(self, fn_node):
        for st in fn_node.body:
            self._stmt(st, eager=False)

    # -- statements -----------------------------------------------------
    def _stmt(self, st, eager: bool):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs are separate call-graph nodes
        self.on_stmt(st, eager)
        if isinstance(st, ast.If):
            side = is_traced_guard_test(st.test)
            self._expr(st.test, eager)
            body_eager = eager or side == "eager"
            else_eager = eager or side == "traced"
            for s in st.body:
                self._stmt(s, body_eager)
            for s in st.orelse:
                self._stmt(s, else_eager)
            return
        for field in ("body", "orelse", "finalbody"):
            for s in getattr(st, field, []) or []:
                self._stmt(s, eager)
        for h in getattr(st, "handlers", []) or []:
            for s in h.body:
                self._stmt(s, eager)
        for e in ast.iter_child_nodes(st):
            if isinstance(e, ast.expr):
                self._expr(e, eager)
            elif isinstance(e, (ast.withitem,)):
                self._expr(e.context_expr, eager)
            elif isinstance(e, ast.ExceptHandler) and e.type:
                self._expr(e.type, eager)

    # -- expressions ----------------------------------------------------
    def _expr(self, e, eager: bool):
        if isinstance(e, ast.IfExp):
            side = is_traced_guard_test(e.test)
            self._expr(e.test, eager)
            self._expr(e.body, eager or side == "eager")
            self._expr(e.orelse, eager or side == "traced")
            return
        if isinstance(e, (ast.Lambda,)):
            self._expr(e.body, eager)
            return
        if isinstance(e, ast.Call):
            self.on_call(e, eager)
        self.on_expr(e, eager)
        for c in ast.iter_child_nodes(e):
            if isinstance(c, ast.expr):
                self._expr(c, eager)
            elif isinstance(c, ast.comprehension):
                self._expr(c.iter, eager)
                for cond in c.ifs:
                    self._expr(cond, eager)

    # -- hooks ----------------------------------------------------------
    def on_call(self, call, eager: bool):
        pass

    def on_stmt(self, st, eager: bool):
        pass

    def on_expr(self, e, eager: bool):
        pass


class _EdgeCollector(_GuardedWalker):
    def __init__(self, graph: "TracedClosure", fi: FuncInfo):
        self.g = graph
        self.fi = fi
        self.edges: list = []

    def on_call(self, call, eager: bool):
        if eager:
            return
        self.edges.extend(self.g.resolve_call(self.fi, call))


class TracedClosure:
    """Computes and holds the set of FuncInfos reachable from traced
    roots; shared by the host-sync and trace-purity passes."""

    def __init__(self, project: Project,
                 kernel_modules: tuple = ("ops.kernels",)):
        self.project = project
        self.roots: list = []
        self.reachable: dict = {}   # (module, qual) -> FuncInfo
        self.root_keys: set = set()
        self._edges_cache: dict = {}
        self._find_roots(kernel_modules)
        self._close()

    # -- root discovery -------------------------------------------------
    def _find_roots(self, kernel_modules):
        for mi in self.project.modules.values():
            short = mi.dotted.split(".", 1)[-1]
            if short in kernel_modules:
                for fi in mi.top_level_functions():
                    if not fi.name.startswith("_"):
                        self._add_root(fi)
            for fi in mi.functions.values():
                for call in ast.walk(fi.node):
                    if not isinstance(call, ast.Call):
                        continue
                    if _call_name(call.func) not in _JIT_NAMES:
                        continue
                    if not call.args:
                        continue
                    a0 = call.args[0]
                    if isinstance(a0, ast.Name):
                        # a local def of the same enclosing function,
                        # or any same-module function of that name
                        target = mi.functions.get(
                            f"{fi.qualname}.{a0.id}") \
                            or mi.functions.get(a0.id)
                        if target is not None:
                            self._add_root(target)

    def _add_root(self, fi: FuncInfo):
        key = (fi.module, fi.qualname)
        if key not in self.root_keys:
            self.root_keys.add(key)
            self.roots.append(fi)

    # -- call resolution ------------------------------------------------
    def resolve_call(self, fi: FuncInfo, call) -> list:
        out = []
        func = call.func
        mi = self.project.modules[fi.module]

        if isinstance(func, ast.Name):
            name = func.id
            # getattr(self, f"_exec_...") dispatch
            if name == "getattr" and len(call.args) >= 2:
                out.extend(self._resolve_getattr(fi, call))
            local = mi.functions.get(f"{fi.qualname}.{name}")
            if local is None and fi.class_name:
                local = mi.functions.get(f"{fi.class_name}.{name}")
            if local is None:
                local = mi.functions.get(name)
            if local is not None:
                out.append(local)
            elif name in mi.import_symbols:
                dmod, attr = mi.import_symbols[name]
                tgt = self.project.function(dmod, attr)
                if tgt is not None:
                    out.append(tgt)
            return out

        if isinstance(func, ast.Attribute):
            attr = func.attr
            val = func.value
            if isinstance(val, ast.Name):
                if val.id in ("self", "cls") and fi.class_name:
                    tgt = mi.functions.get(f"{fi.class_name}.{attr}")
                    if tgt is not None:
                        return [tgt]
                alias = val.id
                if alias in mi.import_modules or \
                        alias in mi.import_symbols:
                    dmod = mi.import_modules.get(alias)
                    if dmod is None:
                        base, sub = mi.import_symbols[alias]
                        dmod = f"{base}.{sub}" if base else sub
                    tgt = self.project.function(dmod, attr)
                    return [tgt] if tgt is not None else []
            # distinctive method name: any scanned class method
            if attr not in GENERIC_NAMES:
                return list(self.project.methods.get(attr, ()))
        return out

    def _resolve_getattr(self, fi: FuncInfo, call) -> list:
        """getattr(self, <f-string with literal prefix>) — the executor
        operator dispatch: expand to matching same-class methods."""
        obj, key = call.args[0], call.args[1]
        if not (isinstance(obj, ast.Name) and obj.id == "self"
                and fi.class_name):
            return []
        prefix = None
        if isinstance(key, ast.JoinedStr) and key.values and \
                isinstance(key.values[0], ast.Constant):
            prefix = str(key.values[0].value)
        elif isinstance(key, ast.Constant) and isinstance(key.value,
                                                          str):
            prefix = key.value
        if not prefix:
            return []
        mi = self.project.modules[fi.module]
        cls_prefix = fi.class_name + "."
        return [f for q, f in mi.functions.items()
                if q.startswith(cls_prefix)
                and f.name.startswith(prefix)]

    def edges_of(self, fi: FuncInfo) -> list:
        key = (fi.module, fi.qualname)
        hit = self._edges_cache.get(key)
        if hit is None:
            col = _EdgeCollector(self, fi)
            col.walk_function(fi.node)
            hit = self._edges_cache[key] = col.edges
        return hit

    # -- closure --------------------------------------------------------
    def _close(self):
        stack = [fi for fi in self.roots if not fi.eager_only]
        for fi in stack:
            self.reachable[(fi.module, fi.qualname)] = fi
        while stack:
            fi = stack.pop()
            for tgt in self.edges_of(fi):
                key = (tgt.module, tgt.qualname)
                if tgt.eager_only or key in self.reachable:
                    continue
                self.reachable[key] = tgt
                stack.append(tgt)

    def __contains__(self, key) -> bool:
        return key in self.reachable

    def functions(self):
        return list(self.reachable.values())
