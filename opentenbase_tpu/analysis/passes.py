"""The four otblint passes.

host-sync
    Inside functions reachable from a traced region, a device value
    must never be forced to the host: ``int()/float()/bool()/len()``
    over a traced expression, ``.item()/.tolist()``, ``np.asarray``,
    ``jax.device_get``, or branching (``if``/``while``) on a traced
    value.  Device-ness is tracked by a light intraprocedural taint:
    results of ``jnp.* / jax.* / ops.kernels / utils.hashing`` calls
    (and anything derived from them) are traced; ``.shape/.dtype``
    reads and static kernel parameters (jit ``static_argnames``,
    int/bool/str-annotated args) are not.  Proven-traced only — the
    pass prefers missing a sync over crying wolf.

trace-purity
    Traced code must be replayable: no ``os.environ`` reads, no
    wall-clock (``time.*``/``datetime.*``), no RNG, no writes to
    module-level state.  Env flags are read at module import or at
    program-key construction — never mid-trace.

program-key
    At every ``ProgramCache.put(key, builder)`` site, each input the
    builder captures (closure free variables, call arguments) must be
    derivable from names that reach the key expression — the
    compiled program's identity must cover everything that shaped it.
    This is the PR-2 staged-array-namespace bug class, enforced.

lock-discipline
    A module-level mutable container in the threaded trees (exec/,
    storage/, gtm/, net/, utils/, obs/) that is written from function
    scope must declare ``# guarded_by: <lock>`` on its definition, and
    every such write must hold that lock (lexical ``with <lock>:`` or a
    ``# holds: <lock>`` contract on the enclosing def).

obs-purity
    Instrumentation must observe the engine, never become part of it:
    no ``obs.trace`` / ``obs.metrics`` call may be reachable inside a
    traced closure (spans would be captured at trace time, re-execute
    never, and their timers would read as zero — silently wrong).
    Spans/events belong at host boundaries only; eager-only regions
    (``if not self._traced:`` branches) are exempt.

net-deadline
    Network conversations in the RPC-bearing modules (net/, gtm/,
    storage/replication.py) must carry a deadline: ``create_connection``
    needs ``timeout=``, and raw ``.recv``/``.sendall``/
    ``settimeout(None)`` are reserved for the frame codecs (wire.py,
    pgwire.py) — everything else flows through send_msg/recv_msg under
    the net/guard.py wrapper, which owns the per-op deadline.
"""

from __future__ import annotations

import ast
import builtins
from typing import Optional

from .callgraph import (TracedClosure, _GuardedWalker,
                        is_traced_guard_test)
from .core import Finding, FuncInfo, Project, _stmt_pragma_lines

_BUILTINS = frozenset(dir(builtins))

#: attribute reads that return static metadata, not device data
_DETAINT_ATTRS = frozenset({"shape", "dtype", "ndim", "itemsize",
                            "names", "types", "dicts"})
#: method calls that force a traced receiver to the host
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
#: container-mutating method names (lock-discipline / trace-purity)
_MUTATORS = frozenset({"append", "add", "update", "pop", "clear",
                       "setdefault", "extend", "remove", "discard",
                       "insert", "popitem", "appendleft", "popleft"})
_SCALAR_ANNOTS = frozenset({"int", "bool", "str", "float", "bytes"})
#: jax/jnp helpers that inspect dtypes statically — their results are
#: host booleans/infos, not traced values
_INTROSPECT = frozenset({"issubdtype", "iinfo", "finfo", "result_type",
                         "promote_types", "can_cast", "isdtype",
                         "dtype"})
#: identity/membership comparisons yield host bools (``x is None``,
#: ``name in batch.cols``) — never tracers
_HOST_CMP = (ast.Is, ast.IsNot, ast.In, ast.NotIn)

_IMPURE_CALL_PREFIXES = ("time.", "datetime.", "random.", "secrets.",
                         "numpy.random.", "uuid.")


def _dotted(expr, mi) -> Optional[str]:
    """Resolve an attribute chain to a dotted name, mapping the root
    through the module's import aliases (``jnp.sum`` -> ``jax.numpy.sum``,
    ``K.compact`` -> ``<pkg>.ops.kernels.compact``)."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    root = expr.id
    if root in mi.import_modules:
        base = mi.import_modules[root]
    elif root in mi.import_symbols:
        mod, attr = mi.import_symbols[root]
        base = f"{mod}.{attr}" if mod else attr
    else:
        base = root
    return ".".join([base] + list(reversed(parts)))


def _func_locals(fn_node) -> set:
    """Names bound inside a function (params + assignments + loop/with
    targets + nested defs); ``global``-declared names are excluded."""
    out, globals_ = set(), set()
    a = fn_node.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        out.add(arg.arg)

    def targets_of(t):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)

    for st in ast.walk(fn_node):
        if st is fn_node:
            continue
        if isinstance(st, ast.Global):
            globals_.update(st.names)
        elif isinstance(st, (ast.Assign,)):
            for t in st.targets:
                targets_of(t)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            targets_of(st.target)
        elif isinstance(st, ast.For):
            targets_of(st.target)
        elif isinstance(st, ast.withitem) and st.optional_vars:
            targets_of(st.optional_vars)
        elif isinstance(st, ast.comprehension):
            targets_of(st.target)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(st.name)
        elif isinstance(st, ast.NamedExpr):
            targets_of(st.target)
        elif isinstance(st, ast.ExceptHandler) and st.name:
            out.add(st.name)
    return out - globals_


def free_vars(fn_node) -> set:
    """Loaded names in a function body that are not bound locally —
    what a closure captures from its environment."""
    bound = _func_locals(fn_node)
    loads = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            loads.add(n.id)
    return loads - bound - _BUILTINS


def _fn_disabled(fi: FuncInfo, rule: str) -> bool:
    return any(fi.src.disabled(ln, rule)
               for ln in _stmt_pragma_lines(fi.node))


class _Emitter:
    def __init__(self, rule: str):
        self.rule = rule
        self.findings: list = []
        self._seen: set = set()

    def emit(self, fi: FuncInfo, line: int, message: str):
        if fi.src.disabled(line, self.rule) or \
                _fn_disabled(fi, self.rule):
            return
        key = (fi.src.rel, line, message)
        if key in self._seen:   # loop bodies are walked twice
            return
        self._seen.add(key)
        self.findings.append(Finding(
            self.rule, fi.src.rel, line, fi.qualname, message))


# ===========================================================================
# host-sync
# ===========================================================================
class HostSyncPass:
    """Taint walk over every function in the traced closure."""

    rule = "host-sync"

    def __init__(self, project: Project, closure: TracedClosure):
        self.project = project
        self.closure = closure

    def run(self) -> list:
        em = _Emitter(self.rule)
        for fi in self.closure.functions():
            self._check(fi, em,
                        taint_params=(fi.module, fi.qualname)
                        in self.closure.root_keys)
        return em.findings

    # -- taint seeds ----------------------------------------------------
    def _static_params(self, fi: FuncInfo) -> set:
        """Params that are static config, not traced data: jit
        static_argnames + scalar-annotated + kwonly args."""
        out = set()
        node = fi.node
        for dec in getattr(node, "decorator_list", []) or []:
            for kw in getattr(dec, "keywords", []) or []:
                if kw.arg == "static_argnames":
                    for el in getattr(kw.value, "elts", []) or []:
                        if isinstance(el, ast.Constant):
                            out.add(str(el.value))
        a = node.args
        for arg in a.kwonlyargs:
            out.add(arg.arg)
        for arg in list(a.posonlyargs) + list(a.args):
            ann = arg.annotation
            if isinstance(ann, ast.Name) and ann.id in _SCALAR_ANNOTS:
                out.add(arg.arg)
            elif isinstance(ann, ast.BinOp):  # "int | None"
                names = {n.id for n in ast.walk(ann)
                         if isinstance(n, ast.Name)}
                if names & _SCALAR_ANNOTS:
                    out.add(arg.arg)
        return out

    def _check(self, fi: FuncInfo, em: _Emitter, taint_params: bool):
        mi = self.project.modules[fi.module]
        env: dict = {}
        if taint_params:
            static = self._static_params(fi)
            a = fi.node.args
            for arg in list(a.posonlyargs) + list(a.args) \
                    + ([a.vararg] if a.vararg else []):
                if arg.arg not in static and \
                        arg.arg not in ("self", "cls"):
                    env[arg.arg] = True

        pkg = self.project.package
        #: local names currently bound to plain Python containers
        #: (list/dict literals) — len()/truthiness on them is host-safe
        #: even when they hold traced elements
        py_containers: set = set()

        def producer(call) -> bool:
            d = _dotted(call.func, mi)
            if d is None:
                return False
            if d.split(".")[-1] in _INTROSPECT:
                return False
            return (d.startswith("jax.")
                    or d == "jax"
                    or d.startswith(f"{pkg}.ops.kernels.")
                    or d.startswith(f"{pkg}.utils.hashing."))

        def taint(e) -> bool:
            if isinstance(e, ast.Name):
                return env.get(e.id, False)
            if isinstance(e, ast.Attribute):
                if e.attr in _DETAINT_ATTRS:
                    return False
                return taint(e.value)
            if isinstance(e, ast.Subscript):
                return taint(e.value)
            if isinstance(e, ast.Call):
                if producer(e):
                    return True
                if isinstance(e.func, ast.Name) and \
                        e.func.id == "getattr" and len(e.args) >= 2 \
                        and isinstance(e.args[1], ast.Constant) \
                        and e.args[1].value in _DETAINT_ATTRS:
                    return False
                args = list(e.args) + [kw.value for kw in e.keywords]
                if any(taint(x) for x in args):
                    return True
                # method on a traced receiver stays traced (.astype,
                # .at[..].set, ...)
                if isinstance(e.func, ast.Attribute) and \
                        taint(e.func.value):
                    return True
                return False
            if isinstance(e, (ast.BinOp,)):
                return taint(e.left) or taint(e.right)
            if isinstance(e, ast.UnaryOp):
                return taint(e.operand)
            if isinstance(e, ast.BoolOp):
                return any(taint(v) for v in e.values)
            if isinstance(e, ast.Compare):
                if all(isinstance(op, _HOST_CMP) for op in e.ops):
                    return False
                return taint(e.left) or any(taint(c)
                                            for c in e.comparators)
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                return any(taint(x) for x in e.elts)
            if isinstance(e, ast.IfExp):
                return taint(e.body) or taint(e.orelse)
            if isinstance(e, ast.NamedExpr):
                return taint(e.value)
            if isinstance(e, ast.Starred):
                return taint(e.value)
            return False

        def check_expr(e, eager: bool):
            """Recursive sink scan (guard-aware via `eager`)."""
            if isinstance(e, ast.IfExp):
                side = is_traced_guard_test(e.test)
                check_expr(e.test, eager)
                check_expr(e.body, eager or side == "eager")
                check_expr(e.orelse, eager or side == "traced")
                if not eager and taint(e.test) and side is None:
                    em.emit(fi, e.lineno,
                            "traced value in conditional expression")
                return
            if isinstance(e, ast.Call) and not eager:
                f = e.func
                if isinstance(f, ast.Name) and e.args:
                    a0 = e.args[0]
                    if f.id in ("int", "float", "bool", "len") and \
                            taint(a0) and not (
                                isinstance(a0, ast.Name)
                                and a0.id in py_containers):
                        em.emit(fi, e.lineno,
                                f"{f.id}() forces a traced value to "
                                f"the host")
                # dotted resolution covers BOTH spellings of a sink:
                # ``jax.device_get(x)`` and ``from jax import
                # device_get; device_get(x)`` map to the same name
                d = _dotted(f, mi) or ""
                if d in ("jax.device_get", "jax.block_until_ready"):
                    em.emit(fi, e.lineno,
                            f"{d}() inside a traced region")
                elif d.startswith("numpy.") and \
                        d.split(".")[-1] in ("asarray", "array",
                                             "copy") and \
                        e.args and taint(e.args[0]):
                    em.emit(fi, e.lineno,
                            "np.%s() copies a traced value to the "
                            "host" % d.split(".")[-1])
                elif isinstance(f, ast.Attribute) and \
                        f.attr in _SYNC_METHODS and taint(f.value):
                    em.emit(fi, e.lineno,
                            f".{f.attr}() forces a traced value "
                            f"to the host")
            for c in ast.iter_child_nodes(e):
                if isinstance(c, ast.expr):
                    check_expr(c, eager)
                elif isinstance(c, ast.comprehension):
                    check_expr(c.iter, eager)
                    for cond in c.ifs:
                        check_expr(cond, eager)

        def assign_target(t, v: bool):
            if isinstance(t, ast.Name):
                env[t.id] = env.get(t.id, False) or v
            elif isinstance(t, (ast.Tuple, ast.List)):
                for x in t.elts:
                    assign_target(x, v)
            elif isinstance(t, ast.Starred):
                assign_target(t.value, v)
            elif isinstance(t, (ast.Subscript, ast.Attribute)):
                # storing a traced value into a container taints the
                # container (cols[n] = a[take])
                root = t
                while isinstance(root, (ast.Subscript, ast.Attribute)):
                    root = root.value
                if isinstance(root, ast.Name) and v:
                    env[root.id] = True

        def host_truthy(test) -> bool:
            """Truthiness of a plain Python container is host-safe."""
            if isinstance(test, ast.UnaryOp) and \
                    isinstance(test.op, ast.Not):
                return host_truthy(test.operand)
            return isinstance(test, ast.Name) and \
                test.id in py_containers

        def is_py_container(v) -> bool:
            if isinstance(v, (ast.List, ast.ListComp, ast.Dict,
                              ast.DictComp, ast.Set, ast.SetComp)):
                return True
            return (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in ("list", "dict", "set", "sorted"))

        def for_targets(st, eager: bool):
            """``for a, b in zip(xs, ys)`` taints a from xs and b from
            ys — not everything from everything (the kernels'
            ``zip(agg_kinds, agg_inputs)`` walks a static kind list
            next to traced columns)."""
            it = st.iter
            if isinstance(it, ast.Call) and \
                    isinstance(it.func, ast.Name) and \
                    isinstance(st.target, ast.Tuple):
                elts = st.target.elts
                if it.func.id == "zip" and len(elts) == len(it.args):
                    for t, src in zip(elts, it.args):
                        assign_target(t, taint(src))
                    return
                if it.func.id == "enumerate" and len(elts) == 2 \
                        and it.args:
                    assign_target(elts[0], False)
                    assign_target(elts[1], taint(it.args[0]))
                    return
            assign_target(st.target, taint(it))

        def walk(stmts, eager: bool):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(st, ast.Assign):
                    check_expr(st.value, eager)
                    v = taint(st.value)
                    for t in st.targets:
                        assign_target(t, v)
                        if isinstance(t, ast.Name):
                            if is_py_container(st.value):
                                py_containers.add(t.id)
                            else:
                                py_containers.discard(t.id)
                elif isinstance(st, ast.AnnAssign):
                    if st.value is not None:
                        check_expr(st.value, eager)
                        assign_target(st.target, taint(st.value))
                        if isinstance(st.target, ast.Name) and \
                                is_py_container(st.value):
                            py_containers.add(st.target.id)
                elif isinstance(st, ast.AugAssign):
                    check_expr(st.value, eager)
                    assign_target(st.target,
                                  taint(st.value) or taint(st.target))
                elif isinstance(st, ast.If):
                    side = is_traced_guard_test(st.test)
                    check_expr(st.test, eager)
                    if not eager and side is None and \
                            taint(st.test) and not host_truthy(st.test):
                        em.emit(fi, st.lineno,
                                "branching on a traced value "
                                "(TracerBoolConversionError at trace "
                                "time)")
                    walk(st.body, eager or side == "eager")
                    walk(st.orelse, eager or side == "traced")
                elif isinstance(st, ast.While):
                    check_expr(st.test, eager)
                    if not eager and taint(st.test) and \
                            not host_truthy(st.test):
                        em.emit(fi, st.lineno,
                                "while-loop over a traced value")
                    walk(st.body, eager)
                    walk(st.body, eager)   # loop-carried taint
                    walk(st.orelse, eager)
                elif isinstance(st, ast.For):
                    check_expr(st.iter, eager)
                    for_targets(st, eager)
                    walk(st.body, eager)
                    walk(st.body, eager)   # loop-carried taint
                    walk(st.orelse, eager)
                elif isinstance(st, ast.With):
                    for item in st.items:
                        check_expr(item.context_expr, eager)
                        if item.optional_vars is not None:
                            assign_target(item.optional_vars,
                                          taint(item.context_expr))
                    walk(st.body, eager)
                elif isinstance(st, ast.Try):
                    walk(st.body, eager)
                    for h in st.handlers:
                        walk(h.body, eager)
                    walk(st.orelse, eager)
                    walk(st.finalbody, eager)
                else:
                    for e in ast.iter_child_nodes(st):
                        if isinstance(e, ast.expr):
                            check_expr(e, eager)

        walk(fi.node.body, eager=False)


# ===========================================================================
# trace-purity
# ===========================================================================
class TracePurityPass:
    rule = "trace-purity"

    def __init__(self, project: Project, closure: TracedClosure):
        self.project = project
        self.closure = closure

    def run(self) -> list:
        em = _Emitter(self.rule)
        for fi in self.closure.functions():
            self._check(fi, em)
        return em.findings

    def _module_global(self, fi: FuncInfo, mi, name: str,
                       locals_: set) -> bool:
        """Whether `name` (not shadowed locally) refers to module-level
        state — of this module or imported from a scanned one."""
        if name in locals_:
            return False
        if name in mi.module_names:
            return True
        if name in mi.import_symbols:
            dmod, attr = mi.import_symbols[name]
            other = self.project.modules.get(dmod)
            return other is not None and attr in other.module_names
        return False

    def _check(self, fi: FuncInfo, em: _Emitter):
        mi = self.project.modules[fi.module]
        locals_ = _func_locals(fi.node)
        globals_decl: set = set()

        def check_expr(e, eager: bool):
            if isinstance(e, ast.IfExp):
                side = is_traced_guard_test(e.test)
                check_expr(e.test, eager)
                check_expr(e.body, eager or side == "eager")
                check_expr(e.orelse, eager or side == "traced")
                return
            if not eager:
                if isinstance(e, ast.Attribute):
                    d = _dotted(e, mi) or ""
                    if d in ("os.environ",):
                        em.emit(fi, e.lineno,
                                "os.environ read mid-trace — snapshot "
                                "at import or into the program key")
                if isinstance(e, ast.Call):
                    d = _dotted(e.func, mi) or ""
                    if d == "os.getenv":
                        em.emit(fi, e.lineno,
                                "os.getenv() mid-trace — snapshot at "
                                "import or into the program key")
                    elif d.startswith(_IMPURE_CALL_PREFIXES):
                        em.emit(fi, e.lineno,
                                f"impure call {d}() inside a traced "
                                f"region")
                    elif isinstance(e.func, ast.Attribute) and \
                            e.func.attr in _MUTATORS:
                        root = e.func.value
                        while isinstance(root, (ast.Subscript,
                                                ast.Attribute)):
                            root = root.value
                        if isinstance(root, ast.Name) and \
                                self._module_global(fi, mi, root.id,
                                                    locals_):
                            em.emit(fi, e.lineno,
                                    f"mutation of module-level "
                                    f"'{root.id}' inside a traced "
                                    f"region")
            for c in ast.iter_child_nodes(e):
                if isinstance(c, ast.expr):
                    check_expr(c, eager)
                elif isinstance(c, ast.comprehension):
                    check_expr(c.iter, eager)
                    for cond in c.ifs:
                        check_expr(cond, eager)

        def check_write(target, lineno: int, eager: bool):
            if eager:
                return
            root = target
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if not isinstance(root, ast.Name):
                return
            name = root.id
            if name in globals_decl or (
                    not isinstance(target, ast.Name)
                    and self._module_global(fi, mi, name, locals_)):
                em.emit(fi, lineno,
                        f"write to module-level '{name}' inside a "
                        f"traced region")

        def walk(stmts, eager: bool):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(st, ast.Global):
                    globals_decl.update(st.names)
                elif isinstance(st, ast.If):
                    side = is_traced_guard_test(st.test)
                    check_expr(st.test, eager)
                    walk(st.body, eager or side == "eager")
                    walk(st.orelse, eager or side == "traced")
                    continue
                elif isinstance(st, ast.Assign):
                    check_expr(st.value, eager)
                    for t in st.targets:
                        check_write(t, st.lineno, eager)
                elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                    if getattr(st, "value", None) is not None:
                        check_expr(st.value, eager)
                    check_write(st.target, st.lineno, eager)
                elif isinstance(st, ast.Delete):
                    for t in st.targets:
                        check_write(t, st.lineno, eager)
                else:
                    for e in ast.iter_child_nodes(st):
                        if isinstance(e, ast.expr):
                            check_expr(e, eager)
                for field in ("body", "orelse", "finalbody"):
                    for s in getattr(st, field, []) or []:
                        walk([s], eager)
                for h in getattr(st, "handlers", []) or []:
                    walk(h.body, eager)

        walk(fi.node.body, eager=False)


# ===========================================================================
# obs-purity
# ===========================================================================
class ObsPurityPass:
    """No tracing/metrics call may execute under a trace: a span opened
    inside a jitted closure is captured once at trace time, never
    re-executed, and times nothing — and ``event()`` would mutate the
    thread-local stack mid-trace.  Flags (a) any call in the traced
    closure resolving into ``<pkg>.obs.`` and (b) any ``obs`` module
    function that becomes reachable from a traced root at all."""

    rule = "obs-purity"

    def __init__(self, project: Project, closure: TracedClosure):
        self.project = project
        self.closure = closure
        self.obs_root = f"{project.package}.obs"

    def run(self) -> list:
        em = _Emitter(self.rule)
        for fi in self.closure.functions():
            if fi.module == self.obs_root or \
                    fi.module.startswith(self.obs_root + "."):
                em.emit(fi, fi.lineno,
                        f"obs function '{fi.qualname}' is reachable "
                        f"from a traced root — instrumentation became "
                        f"part of the program")
                continue
            self._check(fi, em)
        return em.findings

    def _check(self, fi: FuncInfo, em: _Emitter):
        mi = self.project.modules[fi.module]
        prefix = self.obs_root + "."
        obs_root = self.obs_root

        class _W(_GuardedWalker):
            def on_call(self, call, eager: bool):
                if eager:
                    return
                d = _dotted(call.func, mi) or ""
                if d == obs_root or d.startswith(prefix):
                    em.emit(fi, call.lineno,
                            f"instrumentation call {d}() inside a "
                            f"traced region")

        _W().walk_function(fi.node)


# ===========================================================================
# program-key
# ===========================================================================
class ProgramKeyPass:
    rule = "program-key"

    def __init__(self, project: Project):
        self.project = project
        # every module-level name bound to a ProgramCache() anywhere
        self.cache_names: set = set()
        for mi in project.modules.values():
            for st in mi.src.tree.body:
                if isinstance(st, ast.Assign) and \
                        isinstance(st.value, ast.Call):
                    f = st.value.func
                    nm = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else None)
                    if nm == "ProgramCache":
                        for t in st.targets:
                            if isinstance(t, ast.Name):
                                self.cache_names.add(t.id)

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            for fi in mi.functions.values():
                for call in ast.walk(fi.node):
                    if isinstance(call, ast.Call) and \
                            self._is_cache_put(call):
                        self._check_put(mi, fi, call, em)
        return em.findings

    def _is_cache_put(self, call) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "put"
                and len(call.args) >= 2):
            return False
        owner = f.value
        name = owner.id if isinstance(owner, ast.Name) else (
            owner.attr if isinstance(owner, ast.Attribute) else None)
        return name in self.cache_names

    # -- local data-flow ------------------------------------------------
    @staticmethod
    def _assignments(fn_node) -> dict:
        """name -> list of RHS-name sets, from every binding form in the
        function (subscript stores contribute to their root name)."""
        out: dict = {}

        def names_of(e) -> set:
            return {n.id for n in ast.walk(e)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)}

        def bind(t, rhs_names: set):
            if isinstance(t, ast.Name):
                out.setdefault(t.id, []).append(rhs_names)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for x in t.elts:
                    bind(x, rhs_names)
            elif isinstance(t, ast.Starred):
                bind(t.value, rhs_names)
            elif isinstance(t, (ast.Subscript, ast.Attribute)):
                root = t
                extra = set()
                while isinstance(root, (ast.Subscript, ast.Attribute)):
                    if isinstance(root, ast.Subscript):
                        extra |= names_of(root.slice)
                    root = root.value
                if isinstance(root, ast.Name):
                    out.setdefault(root.id, []).append(
                        rhs_names | extra)

        for st in ast.walk(fn_node):
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    bind(t, names_of(st.value))
            elif isinstance(st, (ast.AnnAssign, ast.AugAssign)) and \
                    getattr(st, "value", None) is not None:
                bind(st.target, names_of(st.value))
            elif isinstance(st, ast.For):
                bind(st.target, names_of(st.iter))
            elif isinstance(st, ast.NamedExpr):
                bind(st.target, names_of(st.value))
            elif isinstance(st, ast.withitem) and st.optional_vars:
                bind(st.optional_vars, names_of(st.context_expr))
        return out

    def _check_put(self, mi, fi: FuncInfo, call, em: _Emitter):
        assigns = self._assignments(fi.node)
        key_expr, value_expr = call.args[0], call.args[1]

        # reverse closure: every name that reaches the key expression
        key_names = {n.id for n in ast.walk(key_expr)
                     if isinstance(n, ast.Name)}
        changed = True
        while changed:
            changed = False
            for nm in list(key_names):
                for rhs in assigns.get(nm, ()):
                    new = rhs - key_names
                    if new:
                        key_names |= new
                        changed = True

        module_level = (set(mi.module_names) | set(mi.functions)
                        | set(mi.import_modules)
                        | set(mi.import_symbols)
                        | {f.name for f in mi.top_level_functions()})

        memo: dict = {}

        def covered(name: str, stack: frozenset) -> bool:
            if name in memo:
                return memo[name]
            if name in key_names or name in _BUILTINS or \
                    name in module_level:
                memo[name] = True
                return True
            if name in stack:
                return False
            # a nested def used as the builder: its captures must be
            # covered
            nested = mi.functions.get(f"{fi.qualname}.{name}")
            if nested is not None:
                ok = all(covered(n, stack | {name})
                         for n in free_vars(nested.node))
                memo[name] = ok
                return ok
            # derivable through a local assignment whose inputs are all
            # covered
            for rhs in assigns.get(name, ()):
                if all(covered(n, stack | {name}) for n in rhs):
                    memo[name] = True
                    return True
            memo[name] = False
            return False

        value_names = {n.id for n in ast.walk(value_expr)
                       if isinstance(n, ast.Name)
                       and isinstance(n.ctx, ast.Load)}
        for nm in sorted(value_names):
            if not covered(nm, frozenset()):
                em.emit(fi, call.lineno,
                        f"program builder input '{nm}' does not reach "
                        f"the cache key — a change in it would reuse a "
                        f"stale compiled program")


# ===========================================================================
# lock-discipline
# ===========================================================================
class LockDisciplinePass:
    rule = "lock-discipline"

    def __init__(self, project: Project,
                 trees: tuple = ("exec", "storage", "gtm", "net",
                                 "utils", "obs")):
        self.project = project
        self.trees = trees
        # (module, name) -> {"line", "lock", "module"}
        self.registry: dict = {}
        for mi in project.modules.values():
            if self._in_scope(mi.dotted):
                for name, info in mi.containers.items():
                    self.registry[(mi.dotted, name)] = info

    def _in_scope(self, dotted: str) -> bool:
        parts = dotted.split(".")
        return len(parts) >= 2 and parts[1] in self.trees

    def run(self) -> list:
        em = _Emitter(self.rule)
        mutated_unannotated: dict = {}   # (module, name) -> first site
        for mi in self.project.modules.values():
            if not self._in_scope(mi.dotted):
                continue
            for fi in mi.functions.values():
                self._check_fn(mi, fi, em, mutated_unannotated)
        # one finding per unannotated container, at its definition
        for (dmod, name), (fi, line) in sorted(
                mutated_unannotated.items()):
            info = self.registry[(dmod, name)]
            dmi = self.project.modules[dmod]
            def_line = info["line"]
            if dmi.src.disabled(def_line, self.rule):
                continue
            em.findings.append(Finding(
                self.rule, dmi.src.rel, def_line, "",
                f"module-level mutable '{name}' is written from "
                f"function scope ({fi.src.rel}:{line}) but has no "
                f"# guarded_by: <lock> annotation"))
        # annotations must reference a real module-level lock
        for (dmod, name), info in sorted(self.registry.items()):
            lock = info["lock"]
            dmi = self.project.modules[dmod]
            if lock is not None and lock not in dmi.locks and \
                    not dmi.src.disabled(info["line"], self.rule):
                em.findings.append(Finding(
                    self.rule, dmi.src.rel, info["line"], "",
                    f"'{name}' is guarded_by '{lock}' but no "
                    f"module-level lock of that name exists"))
        return em.findings

    def _resolve(self, mi, name: str) -> Optional[tuple]:
        """(module, name) of a registered container this name refers
        to, following from-imports."""
        if (mi.dotted, name) in self.registry:
            return (mi.dotted, name)
        if name in mi.import_symbols:
            dmod, attr = mi.import_symbols[name]
            if (dmod, attr) in self.registry:
                return (dmod, attr)
        return None

    def _check_fn(self, mi, fi: FuncInfo, em: _Emitter,
                  unannotated: dict):
        locals_ = _func_locals(fi.node)
        held0 = tuple(fi.holds)

        def lock_name(e) -> Optional[str]:
            if isinstance(e, ast.Name):
                return e.id
            if isinstance(e, ast.Attribute):
                return e.attr
            if isinstance(e, ast.Call):
                return None
            return None

        def mutation_root(node) -> Optional[ast.Name]:
            root = node
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            return root if isinstance(root, ast.Name) else None

        def report(name: str, line: int, held):
            if name in locals_:
                return
            key = self._resolve(mi, name)
            if key is None:
                return
            info = self.registry[key]
            lock = info["lock"]
            if lock is None:
                unannotated.setdefault(key, (fi, line))
                return
            if lock not in held:
                em.emit(fi, line,
                        f"write to '{name}' without holding its "
                        f"guarded_by lock '{lock}'")

        def bare_lock_op(st):
            """('acquire'|'release', name) for a statement-level
            ``lock.acquire()`` / ``lock.release()`` call."""
            call = st.value if isinstance(st, ast.Expr) and \
                isinstance(st.value, ast.Call) else None
            if call is None and isinstance(st, ast.Assign) and \
                    isinstance(st.value, ast.Call):
                call = st.value
            if call is None or not isinstance(call.func, ast.Attribute) \
                    or call.func.attr not in ("acquire", "release"):
                return None
            name = lock_name(call.func.value)
            return (call.func.attr, name) if name else None

        def walk(stmts, held: tuple):
            #: locks taken by bare .acquire() earlier in this body —
            #: they stay held across the following sibling statements
            #: (the classic acquire();try:...finally:release() shape)
            bare: list = []
            for st in stmts:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                eff = held + tuple(bare)
                if isinstance(st, ast.With):
                    add = [lock_name(item.context_expr)
                           for item in st.items]
                    walk(st.body, eff + tuple(a for a in add if a))
                    continue
                op = bare_lock_op(st)
                if op is not None:
                    if op[0] == "acquire":
                        bare.append(op[1])
                    elif op[1] in bare:
                        bare.remove(op[1])
                    continue
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if not isinstance(t, ast.Name):
                            r = mutation_root(t)
                            if r is not None:
                                report(r.id, st.lineno, eff)
                elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                    t = st.target
                    if not isinstance(t, ast.Name):
                        r = mutation_root(t)
                        if r is not None:
                            report(r.id, st.lineno, eff)
                elif isinstance(st, ast.Delete):
                    for t in st.targets:
                        r = mutation_root(t)
                        if r is not None and not isinstance(t, ast.Name):
                            report(r.id, st.lineno, eff)
                # mutating method calls in THIS statement's own
                # expressions — nested statements (e.g. a `with lock:`
                # block under an `if`) are walked by the recursion
                # below with their correct held-lock set
                stack: list = [v for f, v in ast.iter_fields(st)
                               if f not in ("body", "orelse",
                                            "finalbody", "handlers")]
                while stack:
                    x = stack.pop()
                    if isinstance(x, list):
                        stack.extend(x)
                        continue
                    if not isinstance(x, ast.AST) or \
                            isinstance(x, ast.stmt):
                        continue
                    if isinstance(x, ast.Call) and \
                            isinstance(x.func, ast.Attribute) and \
                            x.func.attr in _MUTATORS:
                        r = mutation_root(x.func.value)
                        if r is not None:
                            report(r.id, x.lineno, eff)
                    stack.extend(v for _, v in ast.iter_fields(x))
                # nested bodies walked WHOLE so a bare acquire() inside
                # (say) a try body stays held for its later siblings
                for field in ("body", "orelse", "finalbody"):
                    walk(getattr(st, field, []) or [], eff)
                for h in getattr(st, "handlers", []) or []:
                    walk(h.body, eff)

        walk(fi.node.body, held0)


# ===========================================================================
# net-deadline
# ===========================================================================
class NetDeadlinePass:
    """Every network conversation in the RPC-bearing modules must carry
    a deadline.  In scope (``net/``, ``gtm/``, ``storage/replication``):

    - ``socket.create_connection(...)`` must pass ``timeout=`` — a
      connect without one blocks a coordinator thread on a dead peer
      for the kernel default (minutes), starving the pool.
    - raw ``.recv(`` / ``.sendall(`` and ``.settimeout(None)`` are
      reserved for the frame codecs (``net/wire.py``, ``net/pgwire.py``)
      — everything else talks through ``send_msg``/``recv_msg`` under a
      ``guard.guarded`` wrapper, which owns the deadline.

    Per-site escapes use ``# otblint: disable=net-deadline``."""

    rule = "net-deadline"

    def __init__(self, project: Project):
        self.project = project
        pkg = project.package
        self.scope_dirs = (f"{pkg}/net/", f"{pkg}/gtm/")
        self.scope_files = (f"{pkg}/storage/replication.py",)
        self.frame_codecs = (f"{pkg}/net/wire.py", f"{pkg}/net/pgwire.py")

    def _in_scope(self, norm: str) -> bool:
        return norm.startswith(self.scope_dirs) or norm in self.scope_files

    def run(self) -> list:
        import os as _os
        findings = []
        for rel, mi in self.project.by_rel.items():
            norm = rel.replace(_os.sep, "/")
            if not self._in_scope(norm):
                continue
            codec = norm in self.frame_codecs
            self._check_module(mi, codec, findings)
        return findings

    # -- helpers --------------------------------------------------------
    def _enclosing(self, mi, line: int):
        """Innermost function containing `line` (None = module level)."""
        best, best_start = None, -1
        for fi in mi.functions.values():
            node = fi.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end and node.lineno > best_start:
                best, best_start = fi, node.lineno
        return best

    def _emit(self, findings, mi, line: int, message: str):
        src = mi.src
        if src.disabled(line, self.rule):
            return
        fi = self._enclosing(mi, line)
        if fi is not None and _fn_disabled(fi, self.rule):
            return
        findings.append(Finding(self.rule, src.rel, line,
                                fi.qualname if fi else "", message))

    def _check_module(self, mi, codec: bool, findings):
        for node in ast.walk(mi.src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func, mi)
            if d == "socket.create_connection":
                if not any(kw.arg == "timeout" for kw in node.keywords) \
                        and len(node.args) < 2:
                    self._emit(findings, mi, node.lineno,
                               "socket.create_connection without a "
                               "timeout — a dead peer blocks this "
                               "thread for the kernel default")
                continue
            if codec:
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr in ("recv", "sendall"):
                self._emit(findings, mi, node.lineno,
                           f"raw socket .{f.attr}() outside the frame "
                           f"codec — use send_msg/recv_msg under a "
                           f"guard wrapper (deadline ownership)")
            elif f.attr == "settimeout" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value is None:
                self._emit(findings, mi, node.lineno,
                           "settimeout(None) disables the RPC "
                           "deadline on this socket")


# ===========================================================================
# wait-discipline
# ===========================================================================
class WaitDisciplinePass:
    """Every blocking wait on the serving path must be attributed to a
    named wait event.  In scope (``exec/``, ``net/``, ``gtm/``,
    ``storage/``), these calls must run lexically inside a
    ``with ...wait_event("..."):`` block (obs/xray.py) or carry a
    justified ``# otblint: disable=wait-discipline`` pragma:

    - ``<cond-or-event>.wait(...)`` — a Condition/Event park is exactly
      the stall ``otb_wait_events`` exists to explain; an unnamed one
      is invisible to the histogram AND to ``otb_stat_activity``.
    - ``.get(...)`` on a ``queue.Queue`` attribute, and ``.put(...)``
      when that queue was constructed bounded (a bounded put blocks on
      backpressure; ``get_nowait``/unbounded puts never park).
    - ``recv_msg(..., expect_reply=True)`` — the caller is owed a
      reply, so this recv IS the RPC on-wire wait.

    The frame codecs (``net/wire.py``, ``net/pgwire.py``) are exempt —
    they are the mechanism under the named waits, not call sites.
    Method calls on ``self`` named ``wait`` (e.g. ``Scheduler.wait``)
    are wrappers, not primitives — the primitive they park on is
    checked at its own site."""

    rule = "wait-discipline"

    def __init__(self, project: Project):
        self.project = project
        pkg = project.package
        self.scope_dirs = (f"{pkg}/exec/", f"{pkg}/net/",
                          f"{pkg}/gtm/", f"{pkg}/storage/")
        self.exempt_files = (f"{pkg}/net/wire.py", f"{pkg}/net/pgwire.py")

    def _in_scope(self, norm: str) -> bool:
        return norm.startswith(self.scope_dirs) \
            and norm not in self.exempt_files

    def run(self) -> list:
        import os as _os
        findings = []
        for rel, mi in self.project.by_rel.items():
            norm = rel.replace(_os.sep, "/")
            if self._in_scope(norm):
                self._check_module(mi, findings)
        return findings

    # -- helpers --------------------------------------------------------
    def _enclosing(self, mi, line: int):
        best, best_start = None, -1
        for fi in mi.functions.values():
            node = fi.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end and node.lineno > best_start:
                best, best_start = fi, node.lineno
        return best

    def _emit(self, findings, mi, line: int, message: str):
        src = mi.src
        if src.disabled(line, self.rule):
            return
        fi = self._enclosing(mi, line)
        if fi is not None and _fn_disabled(fi, self.rule):
            return
        findings.append(Finding(self.rule, src.rel, line,
                                fi.qualname if fi else "", message))

    @staticmethod
    def _base_name(expr) -> Optional[str]:
        """Last name segment of a call receiver: `self._q` -> `_q`."""
        if isinstance(expr, ast.Attribute):
            return expr.attr
        if isinstance(expr, ast.Name):
            return expr.id
        return None

    def _check_module(self, mi, findings):
        tree = mi.src.tree
        # line intervals of `with ...wait_event(...):` blocks — a wait
        # lexically inside one is attributed, whatever thread runs it
        covered = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                call = item.context_expr
                if isinstance(call, ast.Call):
                    d = _dotted(call.func, mi) or ""
                    if d.split(".")[-1] == "wait_event":
                        covered.append((node.lineno,
                                        getattr(node, "end_lineno",
                                                node.lineno)))
                        break

        def attributed(line: int) -> bool:
            return any(a <= line <= b for a, b in covered)

        # harvest queue.Queue attribute/name assignments; remember
        # which were constructed with a capacity (bounded => put blocks)
        queues, bounded = set(), set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):   # self._q: Queue = ...
                targets = [node.target]
            else:
                continue
            if not isinstance(node.value, ast.Call):
                continue
            d = _dotted(node.value.func, mi) or ""
            if d.split(".")[-1] != "Queue":
                continue
            for t in targets:
                name = self._base_name(t)
                if name is None:
                    continue
                queues.add(name)
                if node.value.args or any(kw.arg == "maxsize"
                                          for kw in node.value.keywords):
                    bounded.add(name)

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            line = node.lineno
            d = _dotted(node.func, mi) or ""
            if d.split(".")[-1] == "recv_msg" and any(
                    kw.arg == "expect_reply"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value for kw in node.keywords):
                if not attributed(line):
                    self._emit(findings, mi, line,
                               "recv_msg(expect_reply=True) outside a "
                               "wait_event context — this recv is the "
                               "RPC on-wire wait; name it")
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            base = self._base_name(f.value)
            if f.attr == "wait":
                # `self.wait(...)` is a wrapper method, not a primitive
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    continue
                if not attributed(line):
                    self._emit(findings, mi, line,
                               f"blocking .wait() on {base or '?'} "
                               f"outside a wait_event context — "
                               f"unnamed stall, invisible to "
                               f"otb_wait_events")
            elif f.attr == "get" and base in queues:
                if not attributed(line):
                    self._emit(findings, mi, line,
                               f"queue {base}.get() outside a "
                               f"wait_event context — an empty queue "
                               f"parks this thread unnamed")
            elif f.attr == "put" and base in bounded:
                if not attributed(line):
                    self._emit(findings, mi, line,
                               f"bounded queue {base}.put() outside a "
                               f"wait_event context — backpressure "
                               f"parks this thread unnamed")


# ===========================================================================
# slot-discipline
# ===========================================================================
class SlotDisciplinePass:
    """Every admission-slot acquire must have a release reachable via
    ``finally``.  A GTM resource-queue slot (``resq_acquire``) or a
    scheduler admission (``_admit``) that a statement dies holding
    shrinks cluster-wide concurrency until the lease reaper notices —
    and with long leases that is minutes of a slot doing nothing.

    Accepted shapes, within the enclosing function:

    - ``acquire(); try: ... finally: release()`` — the ``try`` starts
      at/after the acquire, so every post-acquire exception path runs
      the release; or
    - ``try: acquire(); ... finally: release()`` — the acquire sits
      inside the protected body (release must tolerate not-held, which
      resq_release's owner identity check provides).

    Wrappers that intentionally delegate the release to their caller
    (the scheduler's ``_admit`` itself, the GTM wire passthrough) mark
    the site ``# otblint: disable=slot-discipline``."""

    rule = "slot-discipline"

    _ACQUIRES = ("resq_acquire", "_admit")
    _RELEASES = ("resq_release", "_release", "release",
                 "resq_disconnect")

    def __init__(self, project: Project):
        self.project = project

    def run(self) -> list:
        findings = []
        for mi in self.project.by_rel.values():
            for node in ast.walk(mi.src.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func, mi)
                if d is None or d.split(".")[-1] not in self._ACQUIRES:
                    continue
                self._check_site(mi, node, findings)
        return findings

    # -- helpers --------------------------------------------------------
    def _enclosing(self, mi, line: int):
        best, best_start = None, -1
        for fi in mi.functions.values():
            node = fi.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end and node.lineno > best_start:
                best, best_start = fi, node.lineno
        return best

    def _releases(self, stmts) -> bool:
        for st in stmts:
            for node in ast.walk(st):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func, self._mi)
                    if d is not None and \
                            d.split(".")[-1] in self._RELEASES:
                        return True
        return False

    def _check_site(self, mi, call: ast.Call, findings):
        src = mi.src
        if src.disabled(call.lineno, self.rule):
            return
        fi = self._enclosing(mi, call.lineno)
        if fi is None:
            findings.append(Finding(
                self.rule, src.rel, call.lineno, "",
                "module-level slot acquire cannot pair with a "
                "finally-reachable release"))
            return
        if _fn_disabled(fi, self.rule):
            return
        self._mi = mi
        ok = False
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            if not self._releases(node.finalbody):
                continue
            end = getattr(node, "end_lineno", node.lineno)
            encloses = node.lineno <= call.lineno <= end
            follows = node.lineno >= call.lineno
            if encloses or follows:
                ok = True
                break
        if not ok:
            findings.append(Finding(
                self.rule, src.rel, call.lineno, fi.qualname,
                "slot acquire without a release reachable via "
                "finally — an exception here leaks cluster-wide "
                "admission concurrency until lease expiry"))
