"""StableHLO program audit — otblint rules over exported MLIR.

Extends utils/lowering_check.py's f64 scan into the shared rule/report
machinery: every exported kernel and live fused/mesh program is scanned
for

- ``hlo-f64``            — f64 tensor types (no native TPU support);
- ``hlo-host-transfer``  — genuine host round-trips: send/recv,
  infeed/outfeed, host callbacks.  (``custom_call @Sharding`` is the
  partitioner's layout annotation, not a transfer, and is not flagged);
- ``hlo-dynamic-shape``  — dynamic-shape ops / ``?``-dim tensor types,
  which break AOT compilation caching on TPU.

``python -m opentenbase_tpu.analysis.hlo_audit`` exports the kernel
battery (add ``--full`` for the live query battery with fused/mesh
program capture) and exits nonzero on findings.  The legacy report keys
(``mode``/``f64``/``export_errors``/``kernels``/``programs``/
``battery``/``ok``) are preserved — tests/test_tpu_lowering.py keeps
working against ``utils.lowering_check``, which now delegates here.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

from .core import Finding

# element type in both scalar (tensor<f64>) and shaped (tensor<4xf64>)
# spellings — a plain \b misses the latter ('x' is a word character)
_F64 = re.compile(r"(?:\b|(?<=x))f64\b")
_TRANSFER = re.compile(
    r"stablehlo\.(send|recv|infeed|outfeed)\b"
    r"|custom_call\s*@(xla_python_cpu_callback|xla_ffi_python_cpu_"
    r"callback|HostCompute|xla\.host_transfer)"
    r"|mhlo\.(send|recv)\b")
_DYNSHAPE = re.compile(
    r"stablehlo\.(real_dynamic_slice|dynamic_reshape|dynamic_pad"
    r"|dynamic_broadcast_in_dim|dynamic_gather|dynamic_iota"
    r"|dynamic_conv)\b"
    r"|tensor<(\?|\d+x\?|[0-9x]*\?x)")


def scan_hlo_text(label: str, txt: str) -> list:
    """Scan one exported program's MLIR text; one finding per rule per
    program, at the first offending line."""
    findings = []
    for rule, rx, msg in (
            ("hlo-f64", _F64,
             "f64 tensor type in exported StableHLO"),
            ("hlo-host-transfer", _TRANSFER,
             "host transfer / callback op in exported StableHLO"),
            ("hlo-dynamic-shape", _DYNSHAPE,
             "dynamic-shape op in exported StableHLO")):
        m = rx.search(txt)
        if m:
            line = txt.count("\n", 0, m.start()) + 1
            findings.append(Finding(rule, label, line, "",
                                    f"{msg} ({m.group(0).strip()})"))
    return findings


def _sds_of(tree):
    import jax

    def leaf(a):
        a = jax.numpy.asarray(a)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)
    return jax.tree.map(leaf, tree)


def export_check(fn, args, label: str, report: dict):
    """Export `fn(*args)` for platform 'tpu'; scan the StableHLO and
    record findings (f64 hits also land in the legacy report keys)."""
    import jax
    from jax import export
    try:
        exp = export.export(
            fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn),
            platforms=("tpu",))(*_sds_of(args))
        txt = exp.mlir_module()
    except Exception as e:  # noqa: BLE001 — report, don't crash the scan
        report.setdefault("export_errors", []).append(
            f"{label}: {type(e).__name__}: {e}")
        return
    report["programs"] = report.get("programs", 0) + 1
    for f in scan_hlo_text(label, txt):
        report.setdefault("findings", []).append(f)
        if f.rule == "hlo-f64":
            report.setdefault("f64", []).append(label)


def check_kernels(report: dict):
    """Every ops/kernels.py kernel at two size classes."""
    import jax.numpy as jnp

    from ..ops import kernels as K
    from ..utils.dtypes import device_float
    DF = device_float()
    for n in (1024, 65536):
        f = jnp.zeros(n, DF)
        i = jnp.zeros(n, jnp.int64)
        v = jnp.zeros(n, bool)
        export_check(lambda m, c: K.compact(m, c, out_size=n),
                     (v, (i, f)), f"compact/{n}", report)
        export_check(
            lambda g, m, a: K.grouped_agg_dense(
                g, m, a, num_groups=64,
                agg_kinds=("sum", "count", "min", "max", "sumf")),
            (i, v, (i, i, i, f, f)), f"grouped_agg_dense/{n}", report)
        export_check(
            lambda k, m, a: K.grouped_agg_sort(
                k, m, a, max_groups=n,
                agg_kinds=("sum", "count", "min", "max", "sumf")),
            ((i, i), v, (i, i, i, f, f)),
            f"grouped_agg_sort/{n}", report)
        export_check(K.join_build, (i, v), f"join_build/{n}", report)
        export_check(K.join_probe_counts, (i, i, v),
                     f"join_probe_counts/{n}", report)
        export_check(
            lambda lo, c, p: K.join_expand(lo, c, p, out_size=2 * n,
                                           left_outer=True,
                                           probe_valid=None),
            (i, i, i), f"join_expand/{n}", report)
        export_check(K.semi_mask, (i,), f"semi_mask/{n}", report)
        export_check(lambda c, pv: K.anti_mask(c, pv), (i, v),
                     f"anti_mask/{n}", report)
        export_check(
            lambda k1, k2, m, p1, p2: K.sort_rows(
                (k1, k2), m, (p1, p2), descs=(False, True), limit=128),
            (i, f, v, i, f), f"sort_rows/{n}", report)
        export_check(
            lambda c1, c2: K.bucket_ids((c1, c2), num_buckets=4096),
            (i, i), f"bucket_ids/{n}", report)
        export_check(
            lambda a, b, c, d: K.visibility_mask(
                a, b, c, d, jnp.int64(5), jnp.int64(7), jnp.int64(-1)),
            (i, i, i, i), f"visibility_mask/{n}", report)
    report["kernels"] = report.get("programs", 0)


def audit(full: bool = True) -> dict:
    """Run the audit; returns the combined legacy+findings report."""
    from ..utils.dtypes import mode

    report: dict = {"mode": mode(), "f64": [], "export_errors": [],
                    "findings": []}
    check_kernels(report)

    if full:
        from ..exec import fused, mesh_exec
        from ..utils.lowering_check import run_battery
        seen: set = set()

        def hook(tag, fn, args):
            key = (tag, id(fn))
            if key in seen:
                return
            seen.add(key)
            export_check(fn, args, f"{tag}/{len(seen)}", report)

        fused.EXPORT_HOOK = hook
        mesh_exec.EXPORT_HOOK = hook
        try:
            results = run_battery()
        finally:
            fused.EXPORT_HOOK = None
            mesh_exec.EXPORT_HOOK = None
        report["battery"] = {k: (v if isinstance(v, str) else len(v))
                             for k, v in results.items()}

    # f64 is the documented CONTRACT of x64 mode (bit-matching the CPU
    # oracles) — the hlo-f64 rule only bites under the tpu dtype mode.
    if report["mode"] == "x64":
        for f in report["findings"]:
            if f.rule == "hlo-f64":
                f.suppressed = True
    unsup = [f for f in report["findings"] if not f.suppressed]
    report["unsuppressed"] = len(unsup)
    report["ok"] = (not unsup and not report["export_errors"]
                    and (report["mode"] == "x64" or not report["f64"]))
    report["findings"] = [f.as_dict() for f in report["findings"]]
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="opentenbase_tpu.analysis.hlo_audit",
        description="StableHLO audit of exported engine programs")
    ap.add_argument("--full", action="store_true",
                    help="also run the live query battery and audit "
                         "captured fused/mesh programs")
    ap.add_argument("--kernels-only", action="store_true",
                    help="audit only the kernel battery (fast path "
                         "used by the CI gate)")
    args = ap.parse_args(argv)
    report = audit(full=args.full and not args.kernels_only)
    print(json.dumps(report, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
