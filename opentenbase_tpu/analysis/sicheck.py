"""History-based snapshot-isolation checker (Adya G1 / G-SI).

Input: the bounded read/write history `utils/snapcheck.py` records
under ``$OTB_SNAP_HISTORY`` during the chaos/zipf bench shards —
commits as ``{"t": "w", "sess", "gts", "writes": [[table, version],
...]}`` (post-commit store versions tagged with the commit GTS) and
reads as ``{"t": "r", "sess", "gts", "src", "obs": [[table, version],
...]}`` (src = primary/cache/replica/shared/pool/standby; ``obs`` is
the exact observed version material when the serving tier knows it,
else ``tables`` names the read set and the observed version is
inferred as the latest committed at the read's snapshot GTS).

From the history we build Adya-style dependency edges between
transactions (one committed write event = one write txn; one read
event = one read-only txn):

- ``ww``: per-table version order — the writer of version v depends
  on the writer of the previous version of the same table;
- ``wr``: the writer of the version a read observed → the reader;
- ``rw`` (anti-dependency): a reader that observed version v → the
  writer of the NEXT version of that table.

and reject:

- **future-read** — a read observed a version whose writer committed
  AFTER the read's snapshot GTS (the serve gate let tomorrow's data
  through: exactly what a broken ``snapshot_gts >= tag`` check does);
- **stale-read** — a read observed an OLDER version than the latest
  committed at its snapshot (a cache/replica served data the gate
  should have refused);
- **G1b intermediate-read** — a read observed a non-final version of
  some txn's writes;
- **G1c cycle** — a cycle in wr ∪ ww (impossible when commit GTS
  totally orders writers — checked anyway, it catches corrupt
  histories);
- **G-SI cycle** — a cycle with exactly ONE rw anti-dependency edge:
  for each rw edge r→w, w must not reach r through wr ∪ ww.  (Write
  skew — a cycle with TWO rw edges — is ALLOWED under SI and is not
  flagged.)

Because wr/ww edges strictly increase commit GTS, reachability is
pruned by GTS, keeping the check near-linear on bench histories.

CLI::

    python -m opentenbase_tpu.analysis.sicheck [history.json]
"""

from __future__ import annotations

import json
import sys

__all__ = ["load_history", "check_history", "main"]


def load_history(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return list(data.get("events", []))
    return list(data)


def _normalize(events):
    """(writers, reads): writers is {(table, version): txn}, one txn
    dict per committed write event; reads is a list of read dicts with
    resolved per-table observations."""
    writers: dict = {}          # (table, ver) -> write txn
    by_table: dict = {}         # table -> sorted [(ver, txn)]
    txns: list = []
    reads: list = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        if ev.get("t") == "w":
            txn = {"id": len(txns), "sess": ev.get("sess"),
                   "gts": ev.get("gts"),
                   "writes": [(str(t), int(v))
                              for t, v in ev.get("writes", [])]}
            txns.append(txn)
            for t, v in txn["writes"]:
                writers[(t, v)] = txn
                by_table.setdefault(t, []).append((v, txn))
        elif ev.get("t") == "r":
            reads.append(ev)
    for t in by_table:
        by_table[t].sort(key=lambda x: x[0])
    resolved = []
    for ev in reads:
        gts = ev.get("gts")
        obs = []
        if ev.get("obs"):
            obs = [(str(t), int(v)) for t, v in ev["obs"]]
        elif ev.get("tables") and gts is not None:
            # infer: latest version whose writer committed at or
            # before the read snapshot
            for t in ev["tables"]:
                best = None
                for v, txn in by_table.get(t, []):
                    if txn["gts"] is not None and txn["gts"] <= gts:
                        best = (t, v)
                obs.extend([best] if best else [])
        # reads with no resolvable version material (e.g. a replica
        # fragment whose table set the router doesn't know) still
        # count toward by_source — they witness the tier served, they
        # just contribute no dependency edges
        resolved.append({"sess": ev.get("sess"), "gts": gts,
                         "src": ev.get("src", "?"), "obs": obs,
                         "point": ev.get("point")})
    return writers, by_table, txns, resolved


def check_history(events) -> dict:
    """Run the G1/G-SI analysis; returns ``{"ok", "anomalies",
    "reads", "writes", "by_source"}`` with one dict per anomaly."""
    writers, by_table, txns, reads = _normalize(events)
    anomalies: list = []
    by_source: dict = {}

    # per-txn final version per table (G1b: observing a non-final one
    # is an intermediate read)
    final: dict = {}
    for txn in txns:
        for t, v in txn["writes"]:
            cur = final.get((id(txn), t))
            if cur is None or v > cur:
                final[(id(txn), t)] = v

    # wr / ww / rw edges over txns + read events
    succ: dict = {}             # id(txn) -> set of txn (wr ∪ ww)
    rw_edges: list = []         # (read, observed writer txn, next writer)
    for t, entries in by_table.items():
        for i in range(1, len(entries)):
            a, b = entries[i - 1][1], entries[i][1]
            if a is not b:
                succ.setdefault(id(a), set()).add(id(b))
    txn_by_id = {id(txn): txn for txn in txns}

    def note(kind, read, t, v, extra=""):
        anomalies.append({
            "kind": kind, "table": t, "version": v,
            "src": read.get("src"), "gts": read.get("gts"),
            "sess": read.get("sess"), "detail": extra})

    for read in reads:
        by_source[read["src"]] = by_source.get(read["src"], 0) + 1
        gts = read.get("gts")
        for t, v in read["obs"]:
            w = writers.get((t, v))
            entries = by_table.get(t, [])
            if w is not None:
                if gts is not None and w["gts"] is not None \
                        and w["gts"] > gts:
                    note("future-read", read, t, v,
                         f"writer committed at GTS {w['gts']} > read "
                         f"snapshot {gts}")
                if final.get((id(w), t), v) != v:
                    note("intermediate-read", read, t, v,
                         "observed a non-final version of the "
                         "writer's txn (G1b)")
            if gts is not None and entries:
                latest = None
                for ev_v, txn in entries:
                    if txn["gts"] is not None and txn["gts"] <= gts:
                        latest = ev_v
                if latest is not None and v < latest:
                    note("stale-read", read, t, v,
                         f"latest committed at snapshot {gts} is "
                         f"version {latest}")
            # rw anti-dependency: this read -> writer of the next
            # version of t
            for ev_v, txn in entries:
                if ev_v > v:
                    rw_edges.append((read, txn, t, v))
                    break

    # G1c: cycle in wr ∪ ww between write txns.  wr edges into READS
    # terminate (reads are read-only txns, no outgoing wr/ww), so
    # cycles can only involve writers.  Iterative DFS: a per-table ww
    # chain can be tens of thousands of versions long.
    color: dict = {}
    cyclic_at = None
    for txn in txns:
        root = id(txn)
        if color.get(root, 0):
            continue
        color[root] = 1
        stack = [(root, iter(succ.get(root, ())))]
        while stack and cyclic_at is None:
            nid, it = stack[-1]
            for m in it:
                c = color.get(m, 0)
                if c == 1:
                    cyclic_at = txn
                    break
                if c == 0:
                    color[m] = 1
                    stack.append((m, iter(succ.get(m, ()))))
                    break
            else:
                color[nid] = 2
                stack.pop()
        if cyclic_at is not None:
            break
    if cyclic_at is not None:
        anomalies.append({
            "kind": "g1c-cycle", "table": None, "version": None,
            "src": None, "gts": cyclic_at["gts"],
            "sess": cyclic_at["sess"],
            "detail": "cycle in wr/ww dependency graph"})

    # G-SI: for each rw anti-dependency read->w_next, the cycle closes
    # iff w_next reaches ANY txn that SUPPLIED the read (a wr edge
    # supplier->read) through wr ∪ ww — including w_next itself, the
    # zero-length case where one txn both supplied part of the read
    # and overwrote another part the read missed.  One rw edge in the
    # cycle = G-SIb.  (Write skew needs TWO rw edges and is allowed.)
    # wr/ww edges strictly increase commit GTS, so the search prunes
    # on the suppliers' max GTS.
    for read, w_next, t, v in rw_edges:
        targets: set = set()
        limit = None
        for ot, ov in read["obs"]:
            s = writers.get((ot, ov))
            if s is not None:
                targets.add(id(s))
                if s["gts"] is not None and (limit is None
                                             or s["gts"] > limit):
                    limit = s["gts"]
        if not targets:
            continue
        stack, seen = [id(w_next)], {id(w_next)}
        found = False
        while stack:
            nid = stack.pop()
            if nid in targets:
                found = True
                break
            txn = txn_by_id.get(nid)
            if txn is not None and limit is not None and \
                    txn["gts"] is not None and txn["gts"] > limit:
                continue
            for m in succ.get(nid, ()):
                if m not in seen:
                    seen.add(m)
                    stack.append(m)
        if found:
            note("g-si-cycle", read, t, v,
                 "rw anti-dependency closes a wr/ww path back to a "
                 "txn that supplied this read (G-SIb: cycle with "
                 "exactly one rw edge)")

    return {
        "ok": not anomalies,
        "anomalies": anomalies,
        "reads": len(reads),
        "writes": len(txns),
        "by_source": by_source,
    }


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    import os
    path = argv[0] if argv else os.environ.get("OTB_SNAP_HISTORY", "")
    if not path:
        print("usage: python -m opentenbase_tpu.analysis.sicheck "
              "<history.json>  (or set $OTB_SNAP_HISTORY)",
              file=sys.stderr)
        return 2
    res = check_history(load_history(path))
    json.dump(res, sys.stdout, indent=1, sort_keys=True)
    print()
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
