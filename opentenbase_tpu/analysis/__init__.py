"""otblint — engine-invariant static analysis.

The compiled-query engine lives or dies by invariants the Python
runtime and XLA can't check for us (Flare/Tailwind make the same point
for native Spark/query-accelerator stacks, PAPERS.md):

- code reachable from a traced program (jax.jit / shard_map) must not
  host-sync a traced value (``int(total)`` inside a join kernel turns
  one compiled program into a ping-pong of device round-trips — or a
  TracerBoolConversionError at trace time);
- traced code must be PURE: an ``os.environ`` read mid-trace bakes a
  flag into a cached executable that outlives the flag;
- every input that shapes a compiled program must reach that program's
  cache key (PR 2's staged-array-namespace crash: a post-DML ``__null``
  input changed the program arity under an unchanged key);
- module-level mutable state shared by the threaded CN/DN/GTM servers
  must be written under its declared lock (``# guarded_by: <lock>``).

``python -m opentenbase_tpu.analysis.lint`` runs the four AST passes
over the package and reports JSON findings (rule id + file:line), gated
by a checked-in baseline (``baseline.json``) so pre-existing findings
are explicit and ratcheted — new code scans clean or fails CI.
``analysis/hlo_audit.py`` extends the same rule/report machinery to the
StableHLO of every exported kernel and live fused/mesh program.
"""

from .core import Finding, Project  # noqa: F401
