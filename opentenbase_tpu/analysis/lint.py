"""otblint driver: scan the package, apply the baseline, report.

Usage::

    python -m opentenbase_tpu.analysis.lint [--json] [--root DIR]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules r1,r2]

Exit status is nonzero when unsuppressed findings remain, so the
command gates CI directly (tests/test_lint.py runs it as a subprocess
the same way tests/test_tpu_lowering.py runs the HLO audit).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .callgraph import TracedClosure
from .cardinality import (DeviceResidencyPass, ProgramCardinalityPass,
                          ResultKeyPass, RetraceRiskPass,
                          RetraceWitnessPass, TransferDisciplinePass)
from .concurrency import (ConcurrencyContext, LockAtomicityPass,
                          LockBlockingPass, LockOrderPass,
                          ThreadDaemonPass)
from .core import (Baseline, Project, RULES, default_baseline_path,
                   make_report)
from .passes import (HostSyncPass, LockDisciplinePass, NetDeadlinePass,
                     ObsPurityPass, ProgramKeyPass, SlotDisciplinePass,
                     TracePurityPass, WaitDisciplinePass)
from .visibility import (VersionKeyPass, VisibilityDisciplinePass,
                         VisibilityWitnessPass)

_CONCURRENCY_RULES = {"lock-order", "lock-blocking", "lock-atomicity"}


def repo_root() -> str:
    """Directory containing the ``opentenbase_tpu`` package."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def run_passes(project: Project, rules=None) -> list:
    closure = TracedClosure(project)
    passes = [
        HostSyncPass(project, closure),
        TracePurityPass(project, closure),
        ObsPurityPass(project, closure),
        ProgramKeyPass(project),
        LockDisciplinePass(project),
        NetDeadlinePass(project),
        WaitDisciplinePass(project),
        ThreadDaemonPass(project),
        SlotDisciplinePass(project),
        ProgramCardinalityPass(project, closure),
        ResultKeyPass(project),
        RetraceRiskPass(project, closure),
        DeviceResidencyPass(project),
        TransferDisciplinePass(project, closure),
        RetraceWitnessPass(project),
    ]
    # the witness cross-check consumes the discipline pass's gated
    # set, so the pair shares one scan
    vis = VisibilityDisciplinePass(project)
    passes += [vis, VersionKeyPass(project),
               VisibilityWitnessPass(project, vis)]
    if rules is None or rules & _CONCURRENCY_RULES:
        ctx = ConcurrencyContext(project, closure)
        passes += [
            LockOrderPass(project, ctx),
            LockBlockingPass(project, ctx),
            LockAtomicityPass(project, ctx),
        ]
    findings = []
    for p in passes:
        if rules is None or p.rule in rules:
            findings.extend(p.run())
    return findings


def lint(root=None, package: str = "opentenbase_tpu",
         baseline_path=None, rules=None, rels=None) -> dict:
    """Programmatic entry point; returns the report dict."""
    root = root or repo_root()
    project = Project(root, package, rels=rels)
    findings = run_passes(project, rules=rules)
    baseline = Baseline(baseline_path) if baseline_path else None
    if baseline:
        baseline.apply(findings)
    return make_report(findings, len(project.modules), baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="opentenbase_tpu.analysis.lint",
        description="engine-invariant static analysis (otblint)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON report on stdout")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--baseline", default=None,
                    help="baseline suppression file "
                         "(default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this scan and "
                         "exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset "
                         f"(known: {', '.join(sorted(RULES))})")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only in files changed vs "
                         "the merge-base (OTB_LINT_BASE, origin/main, "
                         "main); the scan itself stays whole-repo so "
                         "cross-file passes see everything")
    ap.add_argument("--github", action="store_true",
                    help="emit GitHub workflow annotations "
                         "(::error file=...,line=...::) for "
                         "unsuppressed findings")
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    bl_path = args.baseline or default_baseline_path()
    project = Project(root, "opentenbase_tpu")
    findings = run_passes(project, rules=rules)

    if args.changed_only:
        changed = _changed_files(root)
        if changed is None:
            print("otblint: --changed-only: no git merge-base found, "
                  "reporting the full scan", file=sys.stderr)
        else:
            findings = [f for f in findings
                        if f.file.replace(os.sep, "/") in changed]

    if args.write_baseline:
        data = Baseline.write(bl_path, findings)
        print(f"wrote {bl_path}: "
              f"{len(data['suppressions'])} suppression keys, "
              f"{len(findings)} findings")
        return 0

    baseline = None if args.no_baseline else Baseline(bl_path)
    if baseline:
        baseline.apply(findings)
    report = make_report(findings, len(project.modules), baseline)

    if args.json:
        json.dump(report, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        for f in sorted(findings, key=lambda x: (x.file, x.line)):
            print(f.render())
        print(f"otblint: {report['files']} files, "
              f"{report['total']} findings "
              f"({report['suppressed']} baseline, "
              f"{report['unsuppressed']} unsuppressed)")
    if args.github:
        for f in sorted(findings, key=lambda x: (x.file, x.line)):
            if not f.suppressed:
                print(f"::error file={f.file},line={f.line}::"
                      f"{f.rule} {f.message}")
    return 0 if report["ok"] else 1


def _changed_files(root: str):
    """Repo-relative paths changed vs the merge-base (committed,
    staged, unstaged, and untracked), or None when no base resolves."""
    import subprocess

    def git(*a):
        r = subprocess.run(["git", *a], cwd=root, capture_output=True,
                           text=True, timeout=30)
        return r.stdout.strip() if r.returncode == 0 else None

    bases = [b for b in (os.environ.get("OTB_LINT_BASE", ""),
                         "origin/main", "main") if b]
    mb = None
    for b in bases:
        mb = git("merge-base", "HEAD", b)
        if mb:
            break
    if not mb:
        return None
    out: set = set()
    diff = git("diff", "--name-only", mb)
    if diff:
        out.update(diff.splitlines())
    untracked = git("ls-files", "--others", "--exclude-standard")
    if untracked:
        out.update(untracked.splitlines())
    return {p.replace(os.sep, "/") for p in out if p}


if __name__ == "__main__":
    raise SystemExit(main())
