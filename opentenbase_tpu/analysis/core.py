"""otblint core: findings, pragmas, module index, baseline ratchet.

The framework is deliberately small: every pass works on plain `ast`
trees plus a per-file pragma table parsed out of comments.  Comment
conventions the passes understand:

``# otblint: disable=rule1,rule2``
    suppress the named rules (or all, with bare ``disable``) on this
    line; on a ``def`` line, for the whole function.
``# otblint: eager-only``  (synonym: ``host-only``)
    on a ``def`` line: this function is never called under a trace —
    the call-graph closure stops here.  Used for executor operators the
    fusability screens reject (cross joins, index/ANN scans) and for
    host-side facades (device-cache staging).
``# otblint: sync-boundary``
    on a ``def`` line: this function is a DECLARED device->host
    materialization boundary (the fused tier's join-overflow read, the
    mesh tier's per-call gather) — transfer-discipline treats its
    pulls as sanctioned.  The annotation is the audit artifact: every
    legal sync in the engine is enumerable by grepping for it.
``# guarded_by: <lock>``
    on a module-level container assignment: writes from function scope
    must hold the named module lock.
``# holds: <lock1>[, lock2]``
    on a ``def`` line: callers are required to hold these locks (the
    plancache ``_evict_lru`` convention), so writes inside are covered.
``# snapshot-gate: <gts-expr>``
    on or inside a ``def``: this function is a declared SERVE POINT —
    it can return cached/replicated/shared state to a reader — and the
    named GTS guard expression (e.g. ``snapshot_gts >= ent[2]``) must
    be discharged by a comparison that lexically dominates the serve,
    or by the gate material flowing into a self-gating source call.
    Checked by the visibility-discipline pass (analysis/visibility.py).
``# version-gate: <version-expr>``
    same, for an exact store-version comparison (e.g.
    ``ent[1].version == ver``) — the entry served must be proven to
    match the live TableStore version.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Optional

#: rule id -> one-line description (the JSON report echoes these)
RULES = {
    "host-sync": "device->host sync of a traced value inside a "
                 "traced region",
    "trace-purity": "impure operation (env/time/RNG/global mutation) "
                    "inside a traced region",
    "program-key": "compiled-program input does not reach the "
                   "program-cache key",
    "lock-discipline": "module-level mutable state written without "
                       "its guarded_by lock",
    "obs-purity": "tracing/metrics instrumentation call inside a "
                  "traced region",
    "net-deadline": "network conversation without a deadline, or raw "
                    "socket I/O outside the frame codec",
    "wait-discipline": "blocking wait (Condition.wait, bounded-queue "
                       "get/put, reply-owed recv) outside a named "
                       "wait_event(...) context",
    "lock-order": "lock-acquisition-order cycle (potential deadlock) "
                  "or a runtime-witnessed edge the static graph lacks",
    "lock-blocking": "blocking operation (RPC, sleep, subprocess, "
                     "device sync, unbounded wait) inside a held-lock "
                     "region",
    "lock-atomicity": "check-then-act across a lock release, or a "
                      "guarded container escaping its lock",
    "thread-daemon": "non-daemon Thread/Timer without an owned join() "
                     "path (hangs interpreter exit)",
    "slot-discipline": "admission-slot acquire (resq_acquire/_admit) "
                       "without a release reachable via finally",
    "program-cardinality": "value with an unbounded domain (raw row "
                           "count, wall clock, RNG, dict iteration "
                           "order) reaches a program-cache key",
    "retrace-risk": "program identity minted per value: unhashable "
                    "key component, ephemeral object id, per-value "
                    "int() of device data, or branching on an "
                    "unquantized shape in traced code",
    "device-residency": "device upload or device-array global storage "
                        "outside the bufferpool staging layer "
                        "(unaccounted under OTB_DEVICE_CACHE_BYTES)",
    "transfer-discipline": "device->host pull (device_get/np.asarray/"
                           ".tolist()) in eager engine code outside a "
                           "declared sync boundary",
    "retrace-witness": "runtime program census diverges from the "
                       "static ladder prediction (non-ladder class, "
                       "unexplained recompile, or compile storm)",
    "result-key": "result-cache key component not derived from the "
                  "masked signature / literal vector / store-version-"
                  "GTS tuple (wall clock, RNG, or a raw row count)",
    "snapshot-gate": "serve point (cache/replica/shared-stream/standby "
                     "read path) without a discharged # snapshot-gate:/"
                     "# version-gate: contract dominating the serve",
    "version-key": "content cache whose values derive from TableStore "
                   "data without store-version material in its key/"
                   "value flow or an invalidation edge — DML cannot "
                   "invalidate it",
    "visibility-witness": "runtime-witnessed serve point (OTB_SNAPCHECK "
                          "shards) absent from the statically-gated "
                          "set, or a recorded sanitizer violation",
    "hlo-f64": "f64 tensor type in exported StableHLO",
    "hlo-host-transfer": "host transfer / callback op in exported "
                         "StableHLO",
    "hlo-dynamic-shape": "dynamic-shape op in exported StableHLO",
}

_PRAGMA = re.compile(r"#\s*otblint:\s*([a-z\-]+)(?:=([\w\-,\s]+))?")
_GUARDED = re.compile(r"#\s*guarded_by:\s*(\w+)")
_HOLDS = re.compile(r"#\s*holds:\s*([\w,\s]+)")
_SNAPGATE = re.compile(r"#\s*snapshot-gate:\s*(.+?)\s*$")
_VERGATE = re.compile(r"#\s*version-gate:\s*(.+?)\s*$")


@dataclasses.dataclass
class Finding:
    rule: str
    file: str          # repo-relative path (or HLO program label)
    line: int
    symbol: str        # enclosing function qualname ("" = module)
    message: str
    suppressed: bool = False

    def key(self) -> tuple:
        """Line-number-free identity used by the baseline ratchet, so
        unrelated edits moving a finding a few lines don't churn it."""
        return (self.rule, self.file, self.symbol)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        sup = " (baseline)" if self.suppressed else ""
        return (f"{self.file}:{self.line}: {self.rule}{sym} "
                f"{self.message}{sup}")


class SourceFile:
    """One parsed source file + its comment-pragma tables."""

    def __init__(self, root: str, rel: str, text: Optional[str] = None):
        self.rel = rel
        self.path = os.path.join(root, rel)
        if text is None:
            with open(self.path, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        # line -> set of disabled rules ({"*"} = all)
        self.disables: dict[int, set] = {}
        # line -> marker set ({"eager-only"})
        self.markers: dict[int, set] = {}
        self.guarded_by: dict[int, str] = {}    # line -> lock name
        self.holds: dict[int, tuple] = {}       # line -> lock names
        self.snapshot_gates: dict[int, str] = {}  # line -> gts expr
        self.version_gates: dict[int, str] = {}   # line -> ver expr
        for i, ln in enumerate(self.lines, 1):
            if "#" not in ln:
                continue
            for m in _PRAGMA.finditer(ln):
                kind, args = m.group(1), m.group(2)
                if kind == "disable":
                    rules = {"*"} if not args else {
                        a.strip() for a in args.split(",") if a.strip()}
                    self.disables.setdefault(i, set()).update(rules)
                elif kind in ("eager-only", "host-only"):
                    self.markers.setdefault(i, set()).add("eager-only")
                elif kind == "sync-boundary":
                    self.markers.setdefault(i, set()).add(
                        "sync-boundary")
            m = _GUARDED.search(ln)
            if m:
                self.guarded_by[i] = m.group(1)
            m = _HOLDS.search(ln)
            if m:
                self.holds[i] = tuple(
                    a.strip() for a in m.group(1).split(",")
                    if a.strip())
            m = _SNAPGATE.search(ln)
            if m:
                self.snapshot_gates[i] = m.group(1)
            m = _VERGATE.search(ln)
            if m:
                self.version_gates[i] = m.group(1)

    def disabled(self, line: int, rule: str) -> bool:
        d = self.disables.get(line)
        return bool(d) and ("*" in d or rule in d)


def _stmt_pragma_lines(node: ast.AST):
    """Candidate comment lines for a statement: its signature lines
    (first line through the line before the body for a multi-line
    def) and the decorator lines above (pragmas ride any of them)."""
    lines = {node.lineno}
    for d in getattr(node, "decorator_list", []) or []:
        lines.add(d.lineno)
    body = getattr(node, "body", None)
    if isinstance(body, list) and body:
        lines.update(range(node.lineno, body[0].lineno))
    return lines


@dataclasses.dataclass
class FuncInfo:
    module: str            # dotted module name
    qualname: str          # e.g. "Executor._exec_hashjoin"
    node: ast.AST          # FunctionDef / AsyncFunctionDef / Lambda
    class_name: Optional[str]
    src: SourceFile
    eager_only: bool = False
    sync_boundary: bool = False
    holds: tuple = ()

    @property
    def lineno(self) -> int:
        return self.node.lineno

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


class ModuleIndex:
    """Per-module symbol tables the passes share: functions (by
    qualname), import aliases, and module-level assigned names."""

    def __init__(self, dotted: str, src: SourceFile):
        self.dotted = dotted
        self.src = src
        self.functions: dict[str, FuncInfo] = {}
        # alias -> dotted module ("jnp" -> "jax.numpy")
        self.import_modules: dict[str, str] = {}
        # alias -> (dotted module, attr)  (from X import Y [as Z])
        self.import_symbols: dict[str, tuple] = {}
        self.module_names: set = set()       # all module-level targets
        self.containers: dict[str, dict] = {}  # mutable module state
        self.locks: set = set()              # module-level lock names
        self._collect()

    # -- construction ---------------------------------------------------
    def _collect(self):
        tree, src = self.src.tree, self.src

        def add_func(node, qual, cls):
            fi = FuncInfo(self.dotted, qual, node, cls, src)
            for ln in _stmt_pragma_lines(node):
                if "eager-only" in src.markers.get(ln, ()):
                    fi.eager_only = True
                if "sync-boundary" in src.markers.get(ln, ()):
                    fi.sync_boundary = True
                if ln in src.holds:
                    fi.holds = fi.holds + src.holds[ln]
            self.functions[qual] = fi

        def walk_body(body, prefix, cls):
            for st in body:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    qual = f"{prefix}{st.name}"
                    add_func(st, qual, cls)
                    walk_body(st.body, qual + ".", cls)
                elif isinstance(st, ast.ClassDef):
                    walk_body(st.body, f"{prefix}{st.name}.",
                              f"{prefix}{st.name}")
                elif isinstance(st, (ast.If, ast.Try, ast.With,
                                     ast.For, ast.While)):
                    for blk in (getattr(st, "body", []),
                                getattr(st, "orelse", []),
                                getattr(st, "finalbody", [])):
                        walk_body(blk, prefix, cls)
                    for h in getattr(st, "handlers", []):
                        walk_body(h.body, prefix, cls)

        walk_body(tree.body, "", None)

        pkg_parts = self.dotted.split(".")
        for st in ast.walk(tree):
            if isinstance(st, ast.Import):
                for al in st.names:
                    self.import_modules[al.asname or
                                        al.name.split(".")[0]] = al.name
            elif isinstance(st, ast.ImportFrom):
                base = st.module or ""
                if st.level:
                    # resolve "from ..ops import kernels" relative to
                    # this module's package
                    anchor = pkg_parts[:-st.level]
                    base = ".".join(anchor + ([base] if base else []))
                for al in st.names:
                    name = al.asname or al.name
                    self.import_symbols[name] = (base, al.name)

        for st in tree.body:
            targets = []
            if isinstance(st, ast.Assign):
                targets = [t for t in st.targets
                           if isinstance(t, ast.Name)]
                value = st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None \
                    and isinstance(st.target, ast.Name):
                targets, value = [st.target], st.value
            else:
                continue
            for t in targets:
                self.module_names.add(t.id)
                if _is_container_expr(value):
                    self.containers[t.id] = {
                        "line": st.lineno,
                        "lock": src.guarded_by.get(st.lineno),
                    }
                if _is_lock_expr(value):
                    self.locks.add(t.id)

    def top_level_functions(self):
        return [fi for q, fi in self.functions.items() if "." not in q]


_CONTAINER_CALLS = {"dict", "list", "set", "defaultdict",
                    "OrderedDict", "deque", "Counter"}


def _is_container_expr(v) -> bool:
    if isinstance(v, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                      ast.ListComp, ast.SetComp)):
        return True
    if isinstance(v, ast.Call):
        f = v.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        return name in _CONTAINER_CALLS
    return False


def _is_lock_expr(v) -> bool:
    if not isinstance(v, ast.Call):
        return False
    f = v.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name in ("Lock", "RLock", "Condition", "Semaphore")


class Project:
    """The scanned file set: by default every ``*.py`` under the
    ``opentenbase_tpu`` package, as one module index per file."""

    def __init__(self, root: str, package: str,
                 rels: Optional[list] = None):
        self.root = root
        self.package = package
        if rels is None:
            rels = []
            pkg_dir = os.path.join(root, package)
            for dirpath, _dirs, files in os.walk(pkg_dir):
                for f in sorted(files):
                    if f.endswith(".py"):
                        rels.append(os.path.relpath(
                            os.path.join(dirpath, f), root))
        self.modules: dict[str, ModuleIndex] = {}
        self.by_rel: dict[str, ModuleIndex] = {}
        for rel in sorted(rels):
            dotted = rel[:-3].replace(os.sep, ".")
            if dotted.endswith(".__init__"):
                dotted = dotted[:-len(".__init__")]
            mi = ModuleIndex(dotted, SourceFile(root, rel))
            self.modules[dotted] = mi
            self.by_rel[rel] = mi
        # global method index: simple name -> [FuncInfo] (class methods
        # only), for distinctive-name attribute-call resolution
        self.methods: dict[str, list] = {}
        for mi in self.modules.values():
            for fi in mi.functions.values():
                if fi.class_name is not None:
                    self.methods.setdefault(fi.name, []).append(fi)

    def function(self, module: str, qual: str) -> Optional[FuncInfo]:
        mi = self.modules.get(module)
        return mi.functions.get(qual) if mi else None


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------
class Baseline:
    """Checked-in suppression file: pre-existing findings are explicit
    and RATCHETED — each (rule, file, symbol) carries the count that
    existed when the baseline was written; any growth is unsuppressed.
    Fixing a finding without refreshing the baseline is always safe
    (stale allowances never fail the gate, they just stop being used)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.allow: dict[tuple, int] = {}
        if path and os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            for ent in data.get("suppressions", []):
                key = (ent["rule"], ent["file"], ent.get("symbol", ""))
                self.allow[key] = int(ent.get("count", 1))

    def apply(self, findings: list) -> None:
        """Mark findings covered by the baseline as suppressed, oldest
        (lowest line) first within each key group."""
        groups: dict[tuple, list] = {}
        for f in findings:
            groups.setdefault(f.key(), []).append(f)
        for key, fs in groups.items():
            quota = self.allow.get(key, 0)
            for f in sorted(fs, key=lambda x: x.line)[:quota]:
                f.suppressed = True

    @staticmethod
    def write(path: str, findings: list) -> dict:
        groups: dict[tuple, int] = {}
        for f in findings:
            groups[f.key()] = groups.get(f.key(), 0) + 1
        data = {
            "comment": "otblint baseline — pre-existing findings, "
                       "ratcheted; regenerate with --write-baseline",
            "suppressions": [
                {"rule": r, "file": fl, "symbol": s, "count": n}
                for (r, fl, s), n in sorted(groups.items())],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        return data


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def make_report(findings: list, files: int,
                baseline: Optional[Baseline]) -> dict:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    unsup = [f for f in findings if not f.suppressed]
    return {
        "files": files,
        "findings": [f.as_dict() for f in
                     sorted(findings, key=lambda x: (x.file, x.line))],
        "counts": counts,
        "total": len(findings),
        "suppressed": len(findings) - len(unsup),
        "unsuppressed": len(unsup),
        "baseline": baseline.path if baseline else None,
        "ok": not unsup,
    }
