"""otbsnap static half: snapshot-visibility soundness passes.

The engine serves reads from five version-sensitive fast paths that
bypass the primary executor — the GTS-versioned result cache and
shared morsel streams, GTS-high-water replica routing, hot standbys,
and version-keyed bufferpool/host-snapshot entries — each guarded by
a hand-written ``snapshot_gts >= tag`` / store-version comparison.
Nothing used to prove a NEW serve path can't skip the gate.  These
passes make the guard set a checked, greppable inventory (the
sync-boundary philosophy), completing the analysis trilogy: otbrace
proved locks, otbcard proved compile keys, otbsnap proves visibility.

Contract comments (parsed by analysis/core.py):

``# snapshot-gate: <gts-expr>``
    on or inside a ``def``: declares the function a SERVE POINT whose
    staleness guard is ``<gts-expr>`` — e.g.
    ``# snapshot-gate: snapshot_gts >= ent[2]`` on
    ``ResultCache.lookup``.  The expression must DISCHARGE: either a
    comparison over exactly its terms appears before a return
    (lexical-dominance approximation), or every term provably flows
    into a call argument / return value (the gate material is live —
    it reaches the self-gating source or the MVCC program run).
``# version-gate: <version-expr>``
    same, for exact store-version matching — e.g.
    ``# version-gate: ent[1].version == ver`` on
    ``DeviceBufferPool.get_chunk``.

Three rules:

- ``snapshot-gate`` (VisibilityDisciplinePass) — every function in
  exec/storage/net/parallel that CALLS a serve source
  (``ResultCache.lookup``, ``ShareHub.attach``, pool
  ``get_chunk``/``get_device``/``host_snapshot``/
  ``peek_host_snapshot``, ``ReplicaRouter.try_exec``, any
  ``exec_plan``/``exec_plan_device`` dispatch) — or IS one of those
  sources — must carry at least one discharged contract.  Ungated
  serve point = finding; a contract whose terms no longer appear in
  the code (stale annotation) = finding.
- ``version-key`` (VersionKeyPass) — a cache container whose written
  values derive from TableStore contents must have store-version /
  GTS material flowing into the write's key or value, or an
  ``invalidate*`` edge on the owning scope; otherwise DML can never
  invalidate it.
- ``visibility-witness`` (VisibilityWitnessPass) — cross-checks the
  runtime witness (``analysis/visibility_witness.json``, written by
  ``utils/snapcheck.py`` under OTB_SNAPCHECK=1 shards): every
  runtime-witnessed serve point must be a member of the
  statically-gated set, and the witness must carry zero recorded
  sanitizer violations.  An unannotated runtime serve path fails CI
  here even if the static detector never saw it.
"""

from __future__ import annotations

import ast
import json
import os

from .cardinality import _assign_exprs, _flow_exprs
from .core import Finding, FuncInfo, Project
from .passes import _Emitter

#: package subtrees where reads can reach a client reply
_SCOPE_DIRS = ("exec", "storage", "net", "parallel")

#: cheap text screen: a module without any of these substrings cannot
#: contain a serve-source call (keeps the whole-repo gate under budget)
_PRE_FILTER = ("exec_plan", ".lookup", ".attach", "get_chunk",
               "get_device", "host_snapshot", "try_exec")

#: serve-source attribute calls that need no receiver check — every
#: plan dispatch must declare which snapshot it serves under
_ANY_RECV_ATTRS = frozenset({"try_exec", "exec_plan",
                             "exec_plan_device"})
_POOL_ATTRS = frozenset({"get_chunk", "get_device", "host_snapshot",
                         "peek_host_snapshot"})

#: (class simple name, method) pairs that ARE the gate — the serving
#: tiers themselves, serve points by definition
_SELF_GATING = frozenset({
    ("ResultCache", "lookup"), ("ShareHub", "attach"),
    ("ReplicaRouter", "try_exec"), ("HotStandby", "exec_plan"),
    ("DeviceBufferPool", "get_chunk"),
    ("DeviceBufferPool", "get_device"),
    ("DeviceBufferPool", "host_snapshot"),
    ("DeviceBufferPool", "peek_host_snapshot"),
})

#: identifiers that count as store-version / snapshot material in a
#: cache write's key+value flow (version-key rule)
_VERSION_TOKENS = frozenset({
    "version", "ver", "vkey", "gts", "version_key", "store_versions",
    "snapshot_ts", "snapshot_gts", "hwm", "commit_ts",
    "last_commit_ts"})

#: calls that read TableStore CONTENT (what makes a cached value
#: version-sensitive in the first place)
_CONTENT_CALLS = frozenset({
    "host_live_columns", "host_snapshot", "peek_host_snapshot",
    "get_chunk", "get_device", "row_count", "column", "columns"})


def _in_scope(dotted: str) -> bool:
    parts = dotted.split(".")
    return len(parts) >= 2 and parts[1] in _SCOPE_DIRS


def _canonical(fi: FuncInfo) -> str:
    """Serve-point name shared with the runtime sanitizer: the dotted
    module minus the package root, plus the qualname — e.g.
    ``exec.share.ResultCache.lookup``."""
    mod = fi.module.split(".", 1)[-1]
    return f"{mod}.{fi.qualname}"


def _own_nodes(fn_node):
    """The nodes a function OWNS: its subtree minus nested function
    bodies (those are separate FuncInfos and carry their own gates)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _node_tokens(node) -> set:
    """Identifier material of an AST subtree: Name ids, Attribute
    attrs, and constant reprs — the terms a gate expression is made
    of."""
    toks: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            toks.add(n.id)
        elif isinstance(n, ast.Attribute):
            toks.add(n.attr)
        elif isinstance(n, ast.Constant):
            toks.add(repr(n.value))
    return toks


def _recv_name(call):
    owner = call.func.value
    if isinstance(owner, ast.Name):
        return owner.id
    if isinstance(owner, ast.Attribute):
        return owner.attr
    return None


# ===========================================================================
# snapshot-gate: visibility discipline
# ===========================================================================
class VisibilityDisciplinePass:
    """Every serve point carries a discharged ``# snapshot-gate:`` /
    ``# version-gate:`` contract.  ``scan()`` also computes the
    statically-gated set the witness cross-check consumes."""

    rule = "snapshot-gate"

    def __init__(self, project: Project):
        self.project = project
        self._scanned = None
        # module-level receiver names bound to the serving singletons
        self.cache_names = {"RESULT_CACHE"}
        self.hub_names = {"HUB"}
        self.pool_names = {"POOL", "self"}
        for mi in project.modules.values():
            for st in mi.src.tree.body:
                if not (isinstance(st, ast.Assign)
                        and isinstance(st.value, ast.Call)):
                    continue
                f = st.value.func
                cls = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                names = {t.id for t in st.targets
                         if isinstance(t, ast.Name)}
                if cls == "ResultCache":
                    self.cache_names |= names
                elif cls == "ShareHub":
                    self.hub_names |= names
                elif cls == "DeviceBufferPool":
                    self.pool_names |= names

    # -- serve-source detection ------------------------------------------
    def _serve_call(self, call):
        """The serve-source kind of a Call, or None."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        a = f.attr
        if a in _ANY_RECV_ATTRS:
            return a
        recv = _recv_name(call)
        if a == "lookup" and recv in self.cache_names:
            return a
        if a == "attach" and recv in self.hub_names:
            return a
        if a in _POOL_ATTRS and recv in self.pool_names:
            return a
        return None

    @staticmethod
    def _self_gating(fi: FuncInfo) -> bool:
        cls = (fi.class_name or "").rsplit(".", 1)[-1]
        return (cls, fi.name) in _SELF_GATING

    # -- gate ownership ----------------------------------------------------
    @staticmethod
    def _gates_by_func(mi) -> dict:
        """qualname -> [(kind, expr, line)]; a gate comment belongs to
        the INNERMOST function whose span covers its line (nested defs
        carry their own contracts).  A gate written ABOVE a ``def``
        (decorator position — only blank/comment/decorator lines
        between) belongs to that def, not the enclosing scope."""
        fis = list(mi.functions.values())
        lines = mi.src.lines

        def decorates(line, fi):
            if not (line < fi.lineno <= line + 8):
                return False
            for ln in lines[line:fi.lineno - 1]:
                t = ln.strip()
                if t and not t.startswith(("#", "@")):
                    return False
            return True

        def owner(line):
            best = None
            for fi in fis:
                if decorates(line, fi):
                    return fi
                end = getattr(fi.node, "end_lineno", None) or fi.lineno
                if fi.lineno <= line <= end and (
                        best is None or fi.lineno > best.lineno):
                    best = fi
            return best

        out: dict = {}
        for table, kind in ((mi.src.snapshot_gates, "snapshot"),
                            (mi.src.version_gates, "version")):
            for line, expr in table.items():
                fi = owner(line)
                if fi is not None:
                    out.setdefault(fi.qualname, []).append(
                        (kind, expr, line))
        return out

    # -- discharge ----------------------------------------------------------
    @staticmethod
    def _used_tokens(fi: FuncInfo, own: list) -> set:
        """Tokens of every call argument and return value, expanded
        through the function's assignment closure — the material that
        provably reaches a callee or the caller.  A gate expression
        whose terms all land here is LIVE: it names the snapshot/
        version operands the function actually serves under."""
        seeds = []
        for n in own:
            if isinstance(n, ast.Call):
                seeds.extend(n.args)
                seeds.extend(kw.value for kw in n.keywords)
            elif isinstance(n, ast.Return) and n.value is not None:
                seeds.append(n.value)
        assigns = _assign_exprs(fi.node)
        toks: set = set()
        seen_names: set = set()
        frontier: list = []

        def absorb(e):
            for x in ast.walk(e):
                if isinstance(x, ast.Name):
                    toks.add(x.id)
                    if x.id not in seen_names:
                        seen_names.add(x.id)
                        frontier.append(x.id)
                elif isinstance(x, ast.Attribute):
                    toks.add(x.attr)
                elif isinstance(x, ast.Constant):
                    toks.add(repr(x.value))

        for e in seeds:
            absorb(e)
        while frontier:
            for rhs, _it in assigns.get(frontier.pop(), ()):
                absorb(rhs)
        return toks

    def _check_gate(self, fi, own, used, kind, expr, line, em):
        try:
            tree = ast.parse(expr, mode="eval")
        except SyntaxError:
            em.emit(fi, line,
                    f"unparseable # {kind}-gate expression {expr!r}")
            return
        want = _node_tokens(tree)
        returns = [n for n in own if isinstance(n, ast.Return)]
        last_ret = max((r.lineno for r in returns), default=None)
        for n in own:
            # mode (a): a comparison over the contract's terms that
            # lexically dominates a return
            if isinstance(n, ast.Compare) and want <= _node_tokens(n) \
                    and (last_ret is None or n.lineno <= last_ret):
                return
        if want <= used:
            return      # mode (b): gate material flows to a call/return
        em.emit(fi, line,
                f"# {kind}-gate: {expr} does not discharge — no "
                f"dominating comparison over its terms and not all of "
                f"them reach a call argument or return value (stale "
                f"contract, or the guard was removed)")

    # -- entry points --------------------------------------------------------
    def scan(self):
        """(findings, gated) — gated is the set of canonical
        serve-point names carrying at least one contract."""
        if self._scanned is not None:
            return self._scanned
        em = _Emitter(self.rule)
        gated: set = set()
        for mi in self.project.modules.values():
            if not _in_scope(mi.dotted):
                continue
            if not any(s in mi.src.text for s in _PRE_FILTER) and \
                    not mi.src.snapshot_gates and \
                    not mi.src.version_gates:
                continue
            gates = self._gates_by_func(mi)
            for fi in mi.functions.values():
                own = list(_own_nodes(fi.node))
                calls = [n for n in own if isinstance(n, ast.Call)
                         and self._serve_call(n) is not None]
                declared = gates.get(fi.qualname, [])
                if declared:
                    gated.add(_canonical(fi))
                if not calls and not self._self_gating(fi):
                    continue
                if not declared:
                    kinds = sorted({self._serve_call(c) for c in calls}
                                   - {None}) or [fi.name]
                    em.emit(fi, calls[0].lineno if calls else fi.lineno,
                            f"serve point ({', '.join(kinds)}) without "
                            f"a # snapshot-gate:/# version-gate: "
                            f"contract — cached/replicated/shared "
                            f"state can reach a reader here with no "
                            f"declared staleness guard")
                    continue
                used = self._used_tokens(fi, own)
                for kind, expr, line in declared:
                    self._check_gate(fi, own, used, kind, expr,
                                     line, em)
        self._scanned = (em.findings, gated)
        return self._scanned

    def gated(self) -> set:
        return self.scan()[1]

    def run(self) -> list:
        return self.scan()[0]


# ===========================================================================
# version-key: content caches DML can actually invalidate
# ===========================================================================
class VersionKeyPass:
    """A cache whose VALUES derive from TableStore contents (column
    pulls, host snapshots, chunk/device entries, row counts) is stale
    the moment DML bumps the store version — so store-version/GTS
    material must flow into the write's key or value (exact-match
    invalidation, the bufferpool convention), or the owning scope must
    expose an ``invalidate*`` edge the bump path can call.  A content
    cache with neither is unreachable by invalidation: flagged."""

    rule = "version-key"

    def __init__(self, project: Project):
        self.project = project

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            if not _in_scope(mi.dotted):
                continue
            if "store" not in mi.src.text.lower():
                continue
            self._scan_module(mi, em)
        return em.findings

    # -- write-site inventory -------------------------------------------
    @staticmethod
    def _write_sites(fi: FuncInfo, recv_names, attr_mode: bool):
        """(container name, key expr, value expr, line) for every
        ``C[k] = v`` / ``C.setdefault(k, v)`` in the function, where C
        is ``self.<name>`` (attr_mode) or a bare module name."""
        sites = []
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and \
                    isinstance(n.targets[0], ast.Subscript):
                tgt = n.targets[0].value
                if attr_mode and isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and tgt.attr in recv_names:
                    sites.append((tgt.attr, n.targets[0].slice,
                                  n.value, n.lineno))
                elif not attr_mode and isinstance(tgt, ast.Name) and \
                        tgt.id in recv_names:
                    sites.append((tgt.id, n.targets[0].slice,
                                  n.value, n.lineno))
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "setdefault" and len(n.args) >= 2:
                tgt = n.func.value
                if attr_mode and isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and tgt.attr in recv_names:
                    sites.append((tgt.attr, n.args[0], n.args[1],
                                  n.lineno))
                elif not attr_mode and isinstance(tgt, ast.Name) and \
                        tgt.id in recv_names:
                    sites.append((tgt.id, n.args[0], n.args[1],
                                  n.lineno))
        return sites

    @staticmethod
    def _flow_tokens(fi: FuncInfo, expr) -> tuple:
        """(identifier tokens, called attr/function names) over the
        expression's assignment-closure flow."""
        toks: set = set()
        calls: set = set()
        for e, _it in _flow_exprs(fi, expr):
            for n in ast.walk(e):
                if isinstance(n, ast.Name):
                    toks.add(n.id)
                elif isinstance(n, ast.Attribute):
                    toks.add(n.attr)
                elif isinstance(n, ast.Call):
                    f = n.func
                    nm = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else None)
                    if nm:
                        calls.add(nm)
        return toks, calls

    def _scan_module(self, mi, em: _Emitter):
        # instance-attribute containers, per class
        by_class: dict = {}
        for fi in mi.functions.values():
            if fi.class_name is None:
                continue
            ent = by_class.setdefault(
                fi.class_name, {"attrs": set(), "fns": [],
                                "inval": []})
            ent["fns"].append(fi)
            if "invalidate" in fi.name or fi.name.startswith("_inval"):
                ent["inval"].append(fi)
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self" and \
                                _is_container(n.value):
                            ent["attrs"].add(t.attr)
        for cls, ent in by_class.items():
            if not ent["attrs"]:
                continue
            invalidated = set()
            for fi in ent["inval"]:
                for n in ast.walk(fi.node):
                    if isinstance(n, ast.Attribute) and \
                            n.attr in ent["attrs"]:
                        invalidated.add(n.attr)
            for fi in ent["fns"]:
                for name, key, val, line in self._write_sites(
                        fi, ent["attrs"], attr_mode=True):
                    if name in invalidated:
                        continue
                    self._check_site(fi, name, key, val, line, em)
        # module-level containers written from function scope
        mod_names = set(mi.containers)
        if mod_names:
            invalidated = {
                name for name in mod_names
                for fi in mi.functions.values()
                if "invalidate" in fi.name
                and any(isinstance(n, ast.Name) and n.id == name
                        for n in ast.walk(fi.node))}
            for fi in mi.functions.values():
                for name, key, val, line in self._write_sites(
                        fi, mod_names - invalidated, attr_mode=False):
                    self._check_site(fi, name, key, val, line, em)

    def _check_site(self, fi, name, key, val, line, em: _Emitter):
        vtoks, vcalls = self._flow_tokens(fi, val)
        if "TableStore" in vcalls:
            # the cached value IS a live store object (catalog entry),
            # not a copy of its contents — it can't go stale
            return
        content = bool(vcalls & _CONTENT_CALLS) or any(
            "store" in t.lower() for t in vtoks | vcalls)
        if not content:
            return
        ktoks, kcalls = self._flow_tokens(fi, key)
        material = (vtoks | ktoks) & _VERSION_TOKENS or \
            (vcalls | kcalls) & _VERSION_TOKENS
        if material:
            return
        em.emit(fi, line,
                f"content cache '{name}' written with TableStore-"
                f"derived data but no store-version/GTS material in "
                f"the entry's key or value and no invalidate* edge — "
                f"DML bumps the store version yet can never invalidate "
                f"this entry")


def _is_container(v) -> bool:
    if isinstance(v, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                      ast.ListComp, ast.SetComp)):
        return True
    if isinstance(v, ast.Call):
        f = v.func
        nm = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        return nm in ("dict", "list", "set", "defaultdict",
                      "OrderedDict", "deque", "Counter")
    return False


# ===========================================================================
# visibility-witness: runtime reality ⊆ static model
# ===========================================================================
def check_witness(data, gated) -> list:
    """Validate a visibility-witness dict against the statically-gated
    serve-point set; returns human-readable violation strings.  Shared
    by VisibilityWitnessPass and the tier-1 witness test."""
    out: list = []
    points = data.get("serve_points", {})
    if not isinstance(points, dict):
        return ["malformed witness: 'serve_points' is not a dict"]
    for name in sorted(points):
        if name not in gated:
            out.append(
                f"runtime-witnessed serve point '{name}' is not in "
                f"the statically-gated set — add a # snapshot-gate:/"
                f"# version-gate: contract on it (or regenerate the "
                f"witness under OTB_SNAPCHECK=1)")
    for v in data.get("violations", []) or []:
        if isinstance(v, dict):
            out.append(
                f"recorded sanitizer violation [{v.get('kind', '?')}] "
                f"at {v.get('point', '?')}: {v.get('message', '')}")
        else:
            out.append(f"recorded sanitizer violation: {v!r}")
    return out


class VisibilityWitnessPass:
    """Cross-check the committed runtime witness
    (analysis/visibility_witness.json, merged across OTB_SNAPCHECK=1
    chaos/zipf shards) against the static gate inventory: witnessed
    serve points ⊆ statically-gated set, zero live violations."""

    rule = "visibility-witness"

    def __init__(self, project: Project,
                 discipline: VisibilityDisciplinePass):
        self.project = project
        self.discipline = discipline

    def run(self) -> list:
        path = os.path.join(self.project.root, self.project.package,
                            "analysis", "visibility_witness.json")
        if not os.path.exists(path):
            return []
        rel = os.path.relpath(path, self.project.root).replace(
            os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            return [Finding(self.rule, rel, 1, "",
                            f"unreadable visibility witness: {e}")]
        return [Finding(self.rule, rel, 1, "", msg)
                for msg in check_witness(data, self.discipline.gated())]
