"""otbrace: concurrency-soundness passes.

Reference analog: PostgreSQL avoids LWLock deadlock by construction —
every lock has a rank (lwlock.c) and acquisition order is a reviewed
invariant, with ``LOCK_DEBUG`` builds asserting it at runtime.  This
module is the same discipline for the engine's threaded surface:

lock-order
    Build the whole-repo lock-acquisition graph: an edge A->B whenever
    code can acquire B while holding A.  Edges come from lexically
    nested ``with lock:`` scopes (including ``with a, b:`` multi-item
    and bare ``.acquire()``/``.release()`` pairs), from ``# holds:``
    contracts on defs, and interprocedurally from the callgraph: a call
    made while holding A contributes A -> every lock in the callee's
    transitive lock footprint.  A cycle in the graph is a potential
    deadlock; the finding shows each edge's witnessing file:line.
    The pass also cross-checks ``analysis/lock_order.json`` — edges
    witnessed at runtime by the ``utils/locks.py`` sanitizer — and
    fails if the static graph is not a superset (no phantom baseline).

lock-blocking
    Inside a held-lock region, flag operations that can stall every
    other thread queued on that lock: unbounded lock/condition waits
    and thread joins (deadlock-capable — the awaited thread may need
    the held lock), and RPC/socket ops, ``time.sleep``,
    ``subprocess``, and device syncs (latency — the serving tier's
    tail-latency killer).

lock-atomicity
    For ``# guarded_by:`` containers: a check-then-act split across a
    lock release (read outside the region that performs the write,
    with no re-validation inside it) and guarded-container escape
    (returning/yielding the container or a live view of it instead of
    a copy — the receiver iterates it unlocked).

thread-daemon
    ``threading.Thread``/``Timer`` created in library code without
    ``daemon=True`` or an owned ``join()`` path leaks a non-daemon
    thread that hangs interpreter exit.

Lock identity is CANONICAL NAMES shared with the runtime sanitizer:
engine locks are created via ``locks.Lock("exec.plancache._LOCK")``
and the registry below prefers that literal string, so a runtime
witnessed edge and a static edge over the same locks agree by
construction.  Locks not created through the factories fall back to a
derived ``<short-module>[.<Class>].<name>`` spelling.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Optional

from .callgraph import TracedClosure
from .core import Finding, FuncInfo, Project
from .passes import _Emitter, _dotted, _func_locals

#: subtrees whose functions get blocking/atomicity findings (the
#: lock-order graph itself spans the whole package)
THREAD_TREES = ("exec", "storage", "gtm", "net", "utils", "obs",
                "catalog", "parallel")

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")
_COPY_CALLS = frozenset({"list", "dict", "tuple", "set", "frozenset",
                         "sorted", "copy", "deepcopy"})
_LIVE_VIEWS = frozenset({"values", "keys", "items"})
_READ_METHODS = frozenset({"get", "items", "keys", "values", "copy"})
_MUTATORS = frozenset({"append", "add", "update", "pop", "clear",
                       "setdefault", "extend", "remove", "discard",
                       "insert", "popitem", "appendleft", "popleft"})

#: site contract for statically-opaque calls (stored callbacks, ship
#: hooks): ``# may-acquire: <canonical-lock>[, ...]`` trailing the
#: statement or on the comment line directly above it declares locks
#: the call may take, feeding the lock-order graph the same way a
#: lexical acquisition would.
_MAY_ACQUIRE_RE = re.compile(r"#\s*may-acquire:\s*([\w.\s,]+)")


def _short(dotted: str) -> str:
    """Module path minus the package root: the spelling canonical lock
    names use (``opentenbase_tpu.exec.plancache`` -> ``exec.plancache``)."""
    return dotted.split(".", 1)[1] if "." in dotted else dotted


def _lock_ctor_kind(v) -> Optional[str]:
    if not isinstance(v, ast.Call):
        return None
    f = v.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name if name in _LOCK_CTORS else None


def _literal_lock_name(call) -> Optional[str]:
    """The canonical-name string argument of a ``locks.Lock("...")`` /
    ``locks.Condition(name="...")`` construction."""
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            return a0.value
    return None


def _looks_lockish(name: str) -> bool:
    """Heuristic for ``with <name>:`` context managers that are locks
    even when the registry cannot resolve them."""
    low = name.lower()
    if any(tok in low for tok in ("lock", "mutex", "cond", "sem")):
        return True
    return low in ("mu", "_mu", "cv", "_cv") or \
        low.endswith(("_mu", "_cv"))


class LockRegistry:
    """Canonical identity for every lock the package creates.

    The literal string passed to the ``utils.locks`` factories wins;
    raw ``threading.*`` locks get a derived name.  ``Condition(lock)``
    aliases to its constructor lock's name — at runtime the condition
    IS that lock."""

    def __init__(self, project: Project):
        self.project = project
        self.module_locks: dict = {}   # (module, name) -> canonical
        self.class_locks: dict = {}    # (module, class, attr) -> canonical
        self.canon: dict = {}          # canonical -> {"kind","file","line"}
        self._attr_canon: dict = {}    # attr -> set of canonicals
        for mi in project.modules.values():
            self._scan_module(mi)
        # second pass: Condition(<lock>) aliases need the lock tables
        for mi in project.modules.values():
            self._scan_aliases(mi)

    # -- construction ---------------------------------------------------
    def _register(self, key_kind: str, key: tuple, canonical: str,
                  kind: str, rel: str, line: int):
        table = self.module_locks if key_kind == "module" \
            else self.class_locks
        table[key] = canonical
        self.canon.setdefault(canonical, {
            "kind": kind, "file": rel, "line": line})
        self._attr_canon.setdefault(key[-1], set()).add(canonical)

    def _scan_module(self, mi):
        rel = mi.src.rel
        for st in mi.src.tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name):
                kind = _lock_ctor_kind(st.value)
                if kind and not self._cond_lock_arg(st.value):
                    name = st.targets[0].id
                    canonical = _literal_lock_name(st.value) or \
                        f"{_short(mi.dotted)}.{name}"
                    self._register("module", (mi.dotted, name),
                                   canonical, kind, rel, st.lineno)
        for fi in mi.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, (ast.Assign, ast.Call)):
                    continue
                if isinstance(node, ast.Assign):
                    tgt = node.targets[0] if len(node.targets) == 1 \
                        else None
                    kind = _lock_ctor_kind(node.value)
                    if kind is None or self._cond_lock_arg(node.value):
                        continue
                    if isinstance(tgt, ast.Name):
                        # function-local literal-named lock (server
                        # closure captures): canon entry only — scoped
                        # resolution happens via local_locks()
                        lit = _literal_lock_name(node.value)
                        if lit:
                            self.canon.setdefault(lit, {
                                "kind": kind, "file": rel,
                                "line": node.lineno})
                            self._attr_canon.setdefault(
                                tgt.id, set()).add(lit)
                        continue
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and fi.class_name:
                        canonical = _literal_lock_name(node.value) or \
                            (f"{_short(mi.dotted)}.{fi.class_name}"
                             f".{tgt.attr}")
                        self._register(
                            "class", (mi.dotted, fi.class_name,
                                      tgt.attr),
                            canonical, kind, rel, node.lineno)
                else:
                    # object.__setattr__(self, "attr", locks.RLock(...))
                    d = _dotted(node.func, mi)
                    if d != "object.__setattr__" or \
                            len(node.args) != 3 or not fi.class_name:
                        continue
                    obj, key, val = node.args
                    kind = _lock_ctor_kind(val)
                    if kind and not self._cond_lock_arg(val) and \
                            isinstance(obj, ast.Name) and \
                            obj.id == "self" and \
                            isinstance(key, ast.Constant):
                        attr = str(key.value)
                        canonical = _literal_lock_name(val) or \
                            (f"{_short(mi.dotted)}.{fi.class_name}"
                             f".{attr}")
                        self._register(
                            "class", (mi.dotted, fi.class_name, attr),
                            canonical, kind, rel, node.lineno)

    @staticmethod
    def _cond_lock_arg(call) -> Optional[ast.expr]:
        """The lock argument of a ``Condition(<lock>)`` construction
        (named conditions — ``Condition(name=...)`` — return None)."""
        if _lock_ctor_kind(call) != "Condition":
            return None
        if call.args and not isinstance(call.args[0], ast.Constant):
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == "lock":
                return kw.value
        return None

    def _scan_aliases(self, mi):
        rel = mi.src.rel
        for fi in mi.functions.values():
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                arg = self._cond_lock_arg(node.value) \
                    if isinstance(node.value, ast.Call) else None
                if arg is None:
                    continue
                base = self.resolve(fi, mi, arg, {})
                if base is None:
                    base = _literal_lock_name(node.value)
                if base is None:
                    continue
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and fi.class_name:
                    self._register(
                        "class", (mi.dotted, fi.class_name, tgt.attr),
                        base, "Condition", rel, node.lineno)
                elif isinstance(tgt, ast.Name) and fi.class_name is None \
                        and node in mi.src.tree.body:
                    self._register("module", (mi.dotted, tgt.id),
                                   base, "Condition", rel, node.lineno)

    # -- resolution -----------------------------------------------------
    def local_locks(self, fi: FuncInfo) -> dict:
        """name -> canonical for function-local ``x = locks.Lock("...")``
        bindings (only literal-named ones are identifiable)."""
        out = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) and \
                    _lock_ctor_kind(node.value):
                lit = _literal_lock_name(node.value)
                if lit:
                    out[node.targets[0].id] = lit
        return out

    def resolve(self, fi: FuncInfo, mi, expr,
                local_locks: dict) -> Optional[str]:
        """Canonical name of the lock an acquisition expression refers
        to, or None when unidentifiable."""
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in local_locks:
                return local_locks[n]
            hit = self.module_locks.get((mi.dotted, n))
            if hit:
                return hit
            if n in mi.import_symbols:
                dmod, attr = mi.import_symbols[n]
                return self.module_locks.get((dmod, attr))
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            val = expr.value
            if isinstance(val, ast.Name):
                if val.id in ("self", "cls") and fi.class_name:
                    hit = self.class_locks.get(
                        (fi.module, fi.class_name, attr))
                    if hit:
                        return hit
                dmod = mi.import_modules.get(val.id)
                if dmod is None and val.id in mi.import_symbols:
                    base, sub = mi.import_symbols[val.id]
                    dmod = f"{base}.{sub}" if base else sub
                if dmod is not None:
                    hit = self.module_locks.get((dmod, attr))
                    if hit:
                        return hit
            # unique attribute name across every registered lock
            cands = self._attr_canon.get(attr, ())
            if len(cands) == 1:
                return next(iter(cands))
        return None

    def reentrant(self, canonical: str) -> bool:
        info = self.canon.get(canonical)
        return bool(info) and info["kind"] in ("RLock", "Condition")


# ---------------------------------------------------------------------------
# per-function lock-flow summaries
# ---------------------------------------------------------------------------
class FnSummary:
    __slots__ = ("fi", "acquires", "calls", "blocked_calls")

    def __init__(self, fi: FuncInfo):
        self.fi = fi
        #: canonical -> (rel, line, qualname) first acquisition site
        self.acquires: dict = {}
        #: [((module, qual), line, held_canonicals_tuple)]
        self.calls: list = []
        #: [(call_node, line, held_entries)] — every call made while at
        #: least one lock (known or lockish-unknown) is held
        self.blocked_calls: list = []


class _HeldWalker:
    """Walks one function body tracking the lexically held lock set:
    ``with`` items (multi-item included), bare ``.acquire()`` /
    ``.release()`` pairs, and ``# holds:`` contract seeds.  Held
    entries are ``(canonical_or_None, spelled, line)``."""

    def __init__(self, registry: LockRegistry, closure: TracedClosure,
                 fi: FuncInfo, mi, summary: FnSummary,
                 instances: Optional[dict] = None):
        self.reg = registry
        self.closure = closure
        self.fi = fi
        self.mi = mi
        self.sum = summary
        # closure capture: a nested def/class (server Handler etc.) can
        # acquire a literal-named lock bound in an ENCLOSING function —
        # merge ancestors' local locks, innermost binding winning
        self.local_locks: dict = {}
        parts = fi.qualname.split(".")
        for i in range(1, len(parts)):
            anc = mi.functions.get(".".join(parts[:i]))
            if anc is not None:
                self.local_locks.update(registry.local_locks(anc))
        self.local_locks.update(registry.local_locks(fi))
        #: (module, var) -> (class_module, class_name) for module-level
        #: ``VAR = ClassName(...)`` singletons (REGISTRY et al.)
        self.instances = instances or {}

    @staticmethod
    def _spelled(e) -> str:
        if isinstance(e, ast.Name):
            return e.id
        if isinstance(e, ast.Attribute):
            return e.attr
        return "<expr>"

    def _lock_entry(self, e, line: int) -> Optional[tuple]:
        canonical = self.reg.resolve(self.fi, self.mi, e,
                                     self.local_locks)
        spelled = self._spelled(e)
        if canonical is None and not _looks_lockish(spelled):
            return None
        return (canonical, spelled, line)

    def walk(self):
        held: list = []
        for name in self.fi.holds:
            canonical = self.reg.module_locks.get(
                (self.fi.module, name)) or \
                (self.reg.class_locks.get(
                    (self.fi.module, self.fi.class_name, name))
                 if self.fi.class_name else None) or \
                (name if name in self.reg.canon else None)
            held.append((canonical, name, self.fi.lineno))
        self._stmts(self.fi.node.body, held)

    def _on_acquire(self, entry: tuple, held: list):
        canonical, _spelled, line = entry
        if canonical is not None and canonical not in self.sum.acquires:
            self.sum.acquires[canonical] = (
                self.fi.src.rel, line, self.fi.qualname)
        held_canons = tuple(c for c, _s, _l in held if c is not None)
        if canonical is not None:
            for a in held_canons:
                if a != canonical:
                    self._edge(a, canonical, line)

    def _edge(self, a: str, b: str, line: int):
        # recorded via the summary's acquires + the pass's edge table;
        # the pass installs this hook
        pass

    def _on_call(self, call, held: list):
        if held:
            self.sum.blocked_calls.append((call, call.lineno,
                                           list(held)))
        held_canons = tuple(
            dict.fromkeys(c for c, _s, _l in held if c is not None))
        # record even lock-free calls: the transitive footprint must
        # flow through lock-free intermediaries (edges themselves only
        # form where held_canons is non-empty)
        for tgt in self._resolve_for_graph(call):
            self.sum.calls.append(((tgt.module, tgt.qualname),
                                   call.lineno, held_canons))

    def _instance_method(self, call) -> Optional[FuncInfo]:
        """``SINGLETON.method(...)`` where SINGLETON is a module-level
        ``VAR = ClassName(...)`` (local or from-imported): resolve to
        the class's method exactly."""
        func = call.func
        if not (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)):
            return None
        v = func.value.id
        inst = self.instances.get((self.fi.module, v))
        if inst is None and v in self.mi.import_symbols:
            inst = self.instances.get(self.mi.import_symbols[v])
        if inst is None:
            return None
        cmod, cls = inst
        return self.closure.project.function(cmod, f"{cls}.{func.attr}")

    def _resolve_for_graph(self, call) -> list:
        """Callgraph resolution, but reject the multi-candidate
        distinctive-method fan-out: a speculative edge here would
        manufacture deadlock cycles."""
        exact_inst = self._instance_method(call)
        if exact_inst is not None:
            return [exact_inst]
        cands = self.closure.resolve_call(self.fi, call)
        if len(cands) > 1 and isinstance(call.func, ast.Attribute):
            v = call.func.value
            exact = isinstance(v, ast.Name) and (
                v.id in ("self", "cls")
                or v.id in self.mi.import_modules
                or v.id in self.mi.import_symbols)
            if not exact:
                return []
        return cands

    def _may_acquire(self, st) -> list:
        """Declared lock names from a ``# may-acquire:`` contract
        trailing this statement or on the pure-comment line above it
        (for calls into stored callbacks the callgraph cannot see)."""
        lines = self.fi.src.lines
        out = []

        def scan(text):
            m = _MAY_ACQUIRE_RE.search(text)
            if m:
                out.extend(n.strip() for n in m.group(1).split(",")
                           if n.strip())

        if 1 <= st.lineno <= len(lines):
            scan(lines[st.lineno - 1])          # trailing
        ln = st.lineno - 1
        while 1 <= ln <= len(lines) and \
                lines[ln - 1].lstrip().startswith("#"):
            scan(lines[ln - 1])                 # comment block above
            ln -= 1
        return out

    # -- statement walk -------------------------------------------------
    def _stmts(self, stmts, held: list):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            for name in self._may_acquire(st):
                self._on_acquire((name, name, st.lineno), held)
            if isinstance(st, ast.With):
                entries = []
                for item in st.items:
                    self._scan_calls(item.context_expr, held)
                    ent = self._lock_entry(item.context_expr,
                                           st.lineno)
                    if ent is not None:
                        self._on_acquire(ent, held + entries)
                        entries.append(ent)
                self._stmts(st.body, held + entries)
                continue
            bare = self._bare_lock_op(st)
            if bare is not None:
                op, ent = bare
                if op == "acquire":
                    self._on_acquire(ent, held)
                    held.append(ent)
                else:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][1] == ent[1] or \
                                (ent[0] is not None
                                 and held[i][0] == ent[0]):
                            held.pop(i)
                            break
                continue
            self._scan_calls(st, held)
            for field in ("body", "orelse", "finalbody"):
                for s in getattr(st, field, []) or []:
                    self._stmts([s], held)
            for h in getattr(st, "handlers", []) or []:
                self._stmts(h.body, held)

    def _bare_lock_op(self, st) -> Optional[tuple]:
        """``lock.acquire()`` / ``lock.release()`` statements (Expr or
        ``ok = lock.acquire(...)``) on a lock-looking receiver."""
        call = None
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
        elif isinstance(st, ast.Assign) and \
                isinstance(st.value, ast.Call):
            call = st.value
        if call is None or not isinstance(call.func, ast.Attribute) or \
                call.func.attr not in ("acquire", "release"):
            return None
        recv = call.func.value
        ent = self._lock_entry(recv, st.lineno)
        if ent is None:
            return None
        if call.func.attr == "acquire":
            # blocking=False acquisitions may fail; their held region is
            # conditional — still record the edge (the success path is
            # what deadlocks) but treat assigns the same as Expr
            self._scan_calls(st, [])
            return ("acquire", ent)
        return ("release", ent)

    def _scan_calls(self, node, held: list):
        """Call sites in this statement's own expressions (nested
        statements recurse separately with their own held set)."""
        stack = [v for f, v in ast.iter_fields(node)
                 if f not in ("body", "orelse", "finalbody",
                              "handlers")] if isinstance(node, ast.stmt) \
            else [node]
        while stack:
            x = stack.pop()
            if isinstance(x, list):
                stack.extend(x)
                continue
            if not isinstance(x, ast.AST) or isinstance(x, ast.stmt):
                continue
            if isinstance(x, ast.Call):
                self._on_call(x, held)
            stack.extend(v for _, v in ast.iter_fields(x))


class ConcurrencyContext:
    """Registry + per-function summaries + the static edge table,
    computed once and shared by the three passes."""

    def __init__(self, project: Project, closure: TracedClosure):
        self.project = project
        self.closure = closure
        self.registry = LockRegistry(project)
        self.instances = self._instance_types()
        self.summaries: dict = {}      # (module, qual) -> FnSummary
        #: (a, b) -> (rel, line, qualname, note)
        self.edges: dict = {}
        self._build()

    def _instance_types(self) -> dict:
        """(module, var) -> (class_module, class_name) for module-level
        ``VAR = ClassName(...)`` singleton assignments, so calls like
        ``REGISTRY.counter(...)`` resolve to the class's method."""

        def is_class(mod: str, name: str) -> bool:
            mi = self.project.modules.get(mod)
            return mi is not None and any(
                q.startswith(name + ".") for q in mi.functions)

        out: dict = {}
        for mi in self.project.modules.values():
            for st in mi.src.tree.body:
                if not (isinstance(st, ast.Assign)
                        and len(st.targets) == 1
                        and isinstance(st.targets[0], ast.Name)
                        and isinstance(st.value, ast.Call)):
                    continue
                func = st.value.func
                tgt = None
                if isinstance(func, ast.Name):
                    if is_class(mi.dotted, func.id):
                        tgt = (mi.dotted, func.id)
                    elif func.id in mi.import_symbols:
                        dmod, cls = mi.import_symbols[func.id]
                        if is_class(dmod, cls):
                            tgt = (dmod, cls)
                elif isinstance(func, ast.Attribute) and \
                        isinstance(func.value, ast.Name):
                    dmod = mi.import_modules.get(func.value.id)
                    if dmod and is_class(dmod, func.attr):
                        tgt = (dmod, func.attr)
                if tgt is not None:
                    out[(mi.dotted, st.targets[0].id)] = tgt
        return out

    def _build(self):
        for mi in self.project.modules.values():
            for fi in mi.functions.values():
                s = FnSummary(fi)
                w = _HeldWalker(self.registry, self.closure, fi, mi, s,
                                self.instances)
                w._edge = self._make_edge_hook(fi)
                w.walk()
                self.summaries[(fi.module, fi.qualname)] = s
        self._interprocedural()

    def _make_edge_hook(self, fi: FuncInfo):
        def hook(a, b, line):
            self.edges.setdefault(
                (a, b), (fi.src.rel, line, fi.qualname, ""))
        return hook

    def _interprocedural(self):
        # transitive lock footprint per function (fixpoint)
        foot = {k: dict(s.acquires) for k, s in self.summaries.items()}
        changed = True
        while changed:
            changed = False
            for k, s in self.summaries.items():
                fk = foot[k]
                for callee, _line, _held in s.calls:
                    for c, site in foot.get(callee, {}).items():
                        if c not in fk:
                            fk[c] = site
                            changed = True
        for k, s in self.summaries.items():
            for callee, line, held in s.calls:
                for c, site in foot.get(callee, {}).items():
                    for a in held:
                        if a != c and (a, c) not in self.edges:
                            self.edges[(a, c)] = (
                                s.fi.src.rel, line, s.fi.qualname,
                                f"via {callee[1]} "
                                f"({site[0]}:{site[1]})")

    def in_thread_tree(self, dotted: str) -> bool:
        parts = dotted.split(".")
        return len(parts) >= 2 and parts[1] in THREAD_TREES


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------
class LockOrderPass:
    rule = "lock-order"

    def __init__(self, project: Project, ctx: ConcurrencyContext):
        self.project = project
        self.ctx = ctx

    def run(self) -> list:
        findings = []
        self._cycles(findings)
        self._cross_check(findings)
        return findings

    def _cycles(self, findings: list):
        adj: dict = {}
        for (a, b) in self.ctx.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        for comp in _sccs(adj):
            if len(comp) < 2:
                continue
            cyc = _find_cycle(adj, sorted(comp))
            parts = []
            for a, b in zip(cyc, cyc[1:] + cyc[:1]):
                rel, line, qual, note = self.ctx.edges.get(
                    (a, b), ("?", 0, "", ""))
                via = f" {note}" if note else ""
                parts.append(f"{a} -> {b} ({rel}:{line}{via})")
            rel0, line0, qual0, _ = self.ctx.edges[(cyc[0], cyc[1])] \
                if len(cyc) > 1 else ("?", 0, "", "")
            findings.append(Finding(
                self.rule, rel0, line0, qual0,
                "potential deadlock: lock-order cycle "
                + "; ".join(parts)))

    def _cross_check(self, findings: list):
        path = os.path.join(self.project.root, self.project.package,
                            "analysis", "lock_order.json")
        if not os.path.exists(path):
            return
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(Finding(
                self.rule, _rel_of(self.project, path), 1, "",
                f"unreadable witnessed-edge file: {e}"))
            return
        rel = _rel_of(self.project, path)
        known = self.ctx.registry.canon
        for pair in data.get("edges", []):
            if not (isinstance(pair, list) and len(pair) == 2):
                continue
            a, b = pair
            unknown = [n for n in (a, b) if n not in known]
            if unknown:
                findings.append(Finding(
                    self.rule, rel, 1, "",
                    f"witnessed lock(s) {unknown} unknown to the "
                    f"static registry — stale lock_order.json, "
                    f"regenerate under OTB_LOCKCHECK=1"))
                continue
            if (a, b) not in self.ctx.edges:
                findings.append(Finding(
                    self.rule, rel, 1, "",
                    f"edge {a} -> {b} witnessed at runtime but absent "
                    f"from the static lock-order graph — the static "
                    f"pass under-approximates reality"))


def _rel_of(project: Project, path: str) -> str:
    return os.path.relpath(path, project.root).replace(os.sep, "/")


def _sccs(adj: dict) -> list:
    """Iterative Tarjan strongly-connected components."""
    index: dict = {}
    low: dict = {}
    on: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _find_cycle(adj: dict, comp: list) -> list:
    """A concrete cycle through an SCC (for the finding message)."""
    comp_set = set(comp)
    start = comp[0]
    path, seen = [start], {start: 0}
    cur = start
    while True:
        nxt = None
        for w in sorted(adj.get(cur, ())):
            if w in comp_set:
                nxt = w
                break
        if nxt is None:
            return path
        if nxt in seen:
            return path[seen[nxt]:]
        seen[nxt] = len(path)
        path.append(nxt)
        cur = nxt


# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------
_SUBPROC = ("subprocess.",)
_SOCKET_ATTRS = frozenset({"connect", "accept", "recv", "sendall",
                           "recv_into", "sendmsg", "recvmsg"})
_RPC_NAMES = frozenset({"send_msg", "recv_msg"})
_DEVICE_SYNC_ATTRS = frozenset({"block_until_ready"})


class LockBlockingPass:
    rule = "lock-blocking"

    def __init__(self, project: Project, ctx: ConcurrencyContext):
        self.project = project
        self.ctx = ctx

    def run(self) -> list:
        em = _Emitter(self.rule)
        for key, s in sorted(self.ctx.summaries.items()):
            if not self.ctx.in_thread_tree(s.fi.module):
                continue
            mi = self.project.modules[s.fi.module]
            for call, line, held in s.blocked_calls:
                self._check(s.fi, mi, call, line, held, em)
        return em.findings

    @staticmethod
    def _unbounded(call) -> bool:
        """No timeout: ``acquire()``, ``wait()``, ``join()`` with no
        bounding argument (``blocking=False`` counts as bounded)."""
        for kw in call.keywords:
            if kw.arg in ("timeout", "blocking"):
                return False
        if call.func.attr == "acquire":
            if call.args:
                a0 = call.args[0]
                if isinstance(a0, ast.Constant) and a0.value is False:
                    return False
                return len(call.args) < 2   # acquire(True) is unbounded
            return True
        return not call.args

    def _held_names(self, held: list) -> str:
        return ", ".join(dict.fromkeys(
            (c or f"'{s}'") for c, s, _l in held))

    def _check(self, fi, mi, call, line, held, em: _Emitter):
        f = call.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        name = f.id if isinstance(f, ast.Name) else None
        d = _dotted(f, mi) or ""
        held_str = self._held_names(held)

        if attr == "join" and not self._is_thread_join(call, d):
            attr = None
        if attr in ("acquire", "wait", "join") and \
                isinstance(f, ast.Attribute):
            recv_canon = self.ctx.registry.resolve(
                fi, mi, f.value, self.ctx.registry.local_locks(fi))
            others = [h for h in held
                      if recv_canon is None or h[0] != recv_canon]
            if attr == "wait" and not others:
                return   # cv.wait() releases the (only) held lock
            if attr in ("acquire", "wait") and recv_canon is None and \
                    not _looks_lockish(self._spelled(f.value)):
                pass     # not a lock-looking receiver; fall through
            elif others or attr == "join":
                if self._unbounded(call):
                    em.emit(fi, line,
                            f"deadlock-capable: unbounded .{attr}() "
                            f"while holding {held_str} — the awaited "
                            f"thread may need the held lock")
                else:
                    em.emit(fi, line,
                            f"latency: bounded .{attr}() wait while "
                            f"holding {held_str}")
                return

        if d == "time.sleep":
            em.emit(fi, line,
                    f"latency: time.sleep() while holding {held_str}")
        elif d.startswith(_SUBPROC):
            em.emit(fi, line,
                    f"latency: subprocess call while holding "
                    f"{held_str}")
        elif d == "socket.create_connection" or attr in _SOCKET_ATTRS:
            em.emit(fi, line,
                    f"latency: socket I/O (.{attr or 'connect'}) "
                    f"while holding {held_str}")
        elif (attr in _RPC_NAMES or name in _RPC_NAMES
              or name == "guarded" or attr == "guarded"):
            em.emit(fi, line,
                    f"latency: RPC while holding {held_str}")
        elif attr in _DEVICE_SYNC_ATTRS or \
                d in ("jax.block_until_ready", "jax.device_get"):
            em.emit(fi, line,
                    f"latency: device sync while holding {held_str}")
        elif d.startswith("numpy.") and \
                d.split(".")[-1] in ("asarray", "array") and \
                self._has_jax_arg(call, mi):
            em.emit(fi, line,
                    f"latency: host gather (np.{d.split('.')[-1]} of "
                    f"a device value) while holding {held_str}")

    @staticmethod
    def _spelled(e) -> str:
        if isinstance(e, ast.Name):
            return e.id
        if isinstance(e, ast.Attribute):
            return e.attr
        return "<expr>"

    @classmethod
    def _is_thread_join(cls, call, dotted: str) -> bool:
        """Distinguish thread.join() from os.path.join / str.join:
        those always take positional arguments, a thread join takes at
        most a timeout."""
        if dotted.startswith(("os.path.", "posixpath.", "ntpath.")):
            return False
        if not call.args and all(kw.arg == "timeout"
                                 for kw in call.keywords):
            return True
        recv = cls._spelled(call.func.value).lower()
        return "thread" in recv or "worker" in recv

    @staticmethod
    def _has_jax_arg(call, mi) -> bool:
        """np.asarray(<jax call result>) — the only np.asarray shape we
        can prove gathers device memory without a taint walk."""
        for a in call.args:
            if isinstance(a, ast.Call):
                d = _dotted(a.func, mi) or ""
                if d.startswith("jax."):
                    return True
        return False


# ---------------------------------------------------------------------------
# lock-atomicity
# ---------------------------------------------------------------------------
class LockAtomicityPass:
    rule = "lock-atomicity"

    def __init__(self, project: Project, ctx: ConcurrencyContext):
        self.project = project
        self.ctx = ctx
        # (module, name) -> lock-name for every guarded container
        self.guarded: dict = {}
        for mi in project.modules.values():
            if not ctx.in_thread_tree(mi.dotted):
                continue
            for name, info in mi.containers.items():
                if info.get("lock"):
                    self.guarded[(mi.dotted, name)] = info["lock"]

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            if not self.ctx.in_thread_tree(mi.dotted):
                continue
            for fi in mi.functions.values():
                self._check_fn(mi, fi, em)
        return em.findings

    def _resolve(self, mi, name: str) -> Optional[tuple]:
        if (mi.dotted, name) in self.guarded:
            return (mi.dotted, name)
        if name in mi.import_symbols:
            dmod, attr = mi.import_symbols[name]
            if (dmod, attr) in self.guarded:
                return (dmod, attr)
        return None

    def _check_fn(self, mi, fi: FuncInfo, em: _Emitter):
        locals_ = _func_locals(fi.node)
        # container key -> {"reads": {region: [lines]},
        #                   "writes": {region: [lines]}}
        events: dict = {}

        def note(key, kind, region, line):
            ev = events.setdefault(key, {"reads": {}, "writes": {}})
            ev[kind].setdefault(region, []).append(line)

        def container_of(e) -> Optional[tuple]:
            if isinstance(e, ast.Name) and e.id not in locals_:
                return self._resolve(mi, e.id)
            return None

        def lock_name(e) -> Optional[str]:
            if isinstance(e, ast.Name):
                return e.id
            if isinstance(e, ast.Attribute):
                return e.attr
            return None

        def scan_exprs(node, region_for: dict):
            """reads/writes in one statement's own expressions."""
            for x in ast.walk(node):
                if isinstance(x, ast.Subscript):
                    key = container_of(x.value)
                    if key is not None:
                        kind = "reads" if isinstance(x.ctx, ast.Load) \
                            else "writes"
                        note(key, kind, region_for.get(
                            self.guarded[key]), x.lineno)
                elif isinstance(x, ast.Compare) and any(
                        isinstance(op, (ast.In, ast.NotIn))
                        for op in x.ops):
                    for c in x.comparators:
                        key = container_of(c)
                        if key is not None:
                            note(key, "reads", region_for.get(
                                self.guarded[key]), x.lineno)
                elif isinstance(x, ast.Call) and \
                        isinstance(x.func, ast.Attribute):
                    key = container_of(x.func.value)
                    if key is not None:
                        kind = "writes" if x.func.attr in _MUTATORS \
                            else ("reads" if x.func.attr
                                  in _READ_METHODS else None)
                        if kind:
                            note(key, kind, region_for.get(
                                self.guarded[key]), x.lineno)

        def walk(stmts, region_for: dict):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(st, ast.With):
                    inner = dict(region_for)
                    for item in st.items:
                        ln = lock_name(item.context_expr)
                        if ln:
                            inner[ln] = id(st)
                        scan_exprs(item.context_expr, region_for)
                    walk(st.body, inner)
                    continue
                if isinstance(st, (ast.Return, ast.Expr)):
                    v = getattr(st, "value", None)
                    if isinstance(st, ast.Return):
                        self._check_escape(mi, fi, v, locals_, em,
                                           "return")
                    elif isinstance(v, (ast.Yield, ast.YieldFrom)):
                        self._check_escape(mi, fi, v.value, locals_,
                                           em, "yield")
                stack = [val for f_, val in ast.iter_fields(st)
                         if f_ not in ("body", "orelse", "finalbody",
                                       "handlers")]
                for x in stack:
                    for n in (x if isinstance(x, list) else [x]):
                        if isinstance(n, ast.AST) and \
                                not isinstance(n, ast.stmt):
                            scan_exprs(n, region_for)
                for field in ("body", "orelse", "finalbody"):
                    for s in getattr(st, field, []) or []:
                        walk([s], region_for)
                for h in getattr(st, "handlers", []) or []:
                    walk(h.body, region_for)

        # ``# holds:`` contract: the whole body is one held region
        region0 = {}
        for name in fi.holds:
            region0[name] = ("holds", name)
        walk(fi.node.body, region0)

        for key, ev in sorted(events.items()):
            self._judge(key, ev, fi, em)

    def _judge(self, key, ev, fi, em: _Emitter):
        name = key[1]
        for wregion, wlines in ev["writes"].items():
            if wregion is None:
                continue   # unlocked writes are lock-discipline's beat
            reads_in = ev["reads"].get(wregion, [])
            reads_out = [ln for r, lns in ev["reads"].items()
                         if r != wregion for ln in lns]
            if reads_out and not reads_in:
                em.emit(fi, min(reads_out),
                        f"check-then-act on '{name}': read at line "
                        f"{min(reads_out)} is outside the lock region "
                        f"that writes it (line {min(wlines)}) — "
                        f"re-validate under the lock")

    def _check_escape(self, mi, fi, value, locals_, em: _Emitter,
                      how: str):
        if value is None:
            return
        def guarded_name(e) -> Optional[str]:
            if isinstance(e, ast.Name) and e.id not in locals_ and \
                    self._resolve(mi, e.id) is not None:
                return e.id
            return None

        name = guarded_name(value)
        if name:
            em.emit(fi, value.lineno,
                    f"guarded-container escape: {how} of '{name}' — "
                    f"the caller iterates it outside its lock; "
                    f"{how} a copy")
            return
        if isinstance(value, ast.Call):
            f = value.func
            cname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if cname in _COPY_CALLS:
                return
            if isinstance(f, ast.Attribute) and f.attr in _LIVE_VIEWS:
                name = guarded_name(f.value)
                if name:
                    em.emit(fi, value.lineno,
                            f"guarded-container escape: {how} of live "
                            f"view '{name}.{f.attr}()' — materialize "
                            f"a copy under the lock")
            elif cname == "iter" and value.args:
                name = guarded_name(value.args[0])
                if name:
                    em.emit(fi, value.lineno,
                            f"guarded-container escape: {how} of live "
                            f"iterator over '{name}'")


# ---------------------------------------------------------------------------
# thread-daemon
# ---------------------------------------------------------------------------
class ThreadDaemonPass:
    rule = "thread-daemon"

    def __init__(self, project: Project):
        self.project = project

    def run(self) -> list:
        em = _Emitter(self.rule)
        for mi in self.project.modules.values():
            self._check_module(mi, em)
        return em.findings

    @staticmethod
    def _thread_ctor(call, mi) -> Optional[str]:
        d = _dotted(call.func, mi) or ""
        if d in ("threading.Thread", "threading.Timer"):
            return d.split(".")[-1]
        return None

    @staticmethod
    def _daemon_kwarg(call) -> Optional[bool]:
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return None

    def _check_module(self, mi, em: _Emitter):
        # names/attrs that get .join() or .daemon = True anywhere in
        # the module: an "owned" lifecycle
        joined: set = set()
        daemonized: set = set()
        for node in ast.walk(mi.src.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                base = node.func.value
                nm = base.attr if isinstance(base, ast.Attribute) else (
                    base.id if isinstance(base, ast.Name) else None)
                if nm:
                    joined.add(nm)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon" and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value is True:
                        base = t.value
                        nm = base.attr if isinstance(base,
                                                     ast.Attribute) \
                            else (base.id if isinstance(base, ast.Name)
                                  else None)
                        if nm:
                            daemonized.add(nm)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "setDaemon":
                base = node.func.value
                nm = base.attr if isinstance(base, ast.Attribute) else (
                    base.id if isinstance(base, ast.Name) else None)
                if nm:
                    daemonized.add(nm)

        for fi in mi.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._thread_ctor(node, mi)
                if kind is None:
                    continue
                dk = self._daemon_kwarg(node)
                if dk is True:
                    continue
                bound = self._bound_name(mi, node)
                if bound and (bound in joined or bound in daemonized):
                    continue
                if dk is False:
                    em.emit(fi, node.lineno,
                            f"{kind} created with daemon=False and no "
                            f"owned join() — hangs interpreter exit")
                    continue
                em.emit(fi, node.lineno,
                        f"{kind} created without daemon=True or an "
                        f"owned join() path — a leaked non-daemon "
                        f"thread hangs interpreter exit")

        # Thread subclasses must daemonize in __init__ (or every
        # instantiation site is on its own, which we can't see)
        for st in ast.walk(mi.src.tree):
            if not isinstance(st, ast.ClassDef):
                continue
            if not any(self._is_thread_base(b, mi) for b in st.bases):
                continue
            if self._class_daemonizes(st):
                continue
            if mi.src.disabled(st.lineno, self.rule):
                continue
            em.findings.append(Finding(
                self.rule, mi.src.rel, st.lineno, st.name,
                f"threading.Thread subclass '{st.name}' never sets "
                f"daemon=True — instances leak non-daemon threads"))

    @staticmethod
    def _bound_name(mi, call) -> Optional[str]:
        for node in ast.walk(mi.src.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    return t.id
                if isinstance(t, ast.Attribute):
                    return t.attr
        return None

    @staticmethod
    def _is_thread_base(base, mi) -> bool:
        d = _dotted(base, mi) or ""
        return d in ("threading.Thread", "Thread")

    @staticmethod
    def _class_daemonizes(cls_node) -> bool:
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon" and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value is True:
                        return True
                    if isinstance(t, ast.Name) and t.id == "daemon" \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is True:
                        return True
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "daemon" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value is True:
                        return True
        return False


# ---------------------------------------------------------------------------
# public helpers (tests + tooling)
# ---------------------------------------------------------------------------
def build_context(root: str, package: str = "opentenbase_tpu",
                  ) -> ConcurrencyContext:
    project = Project(root, package)
    return ConcurrencyContext(project, TracedClosure(project))


def lock_order_edges(root: str, package: str = "opentenbase_tpu",
                     ) -> dict:
    """(a, b) -> site tuple — the repo's static lock-order graph."""
    return build_context(root, package).edges
