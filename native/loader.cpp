// otbloader — native delimited-text loader for the columnar engine.
//
// Reference analog: the COPY FROM parse path (src/backend/commands/copy.c
// CopyReadLine/CopyReadAttributes — the reference's bulk-ingest hot loop is
// C; ours is too).  Two-pass contract with Python:
//   1. otb_count_rows(path) -> row count (and validates terminators)
//   2. caller allocates numpy buffers, otb_parse fills them in one pass
//
// Column kinds: 0=int64, 1=float64, 2=decimal(scale)->scaled int64,
// 3=date(YYYY-MM-DD)->int32 days since epoch, 4=text->fixed-width bytes.
//
// Build: g++ -O3 -shared -fPIC loader.cpp -o libotbloader.so

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// days since 1970-01-01 for a Gregorian date (Howard Hinnant's
// days_from_civil, public-domain algorithm)
static int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
    y -= m <= 2;
    const int64_t era = (y >= 0 ? y : y - 399) / 400;
    const int64_t yoe = y - era * 400;
    const int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    const int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + doe - 719468;
}

long long otb_count_rows(const char* path) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    static const size_t BUF = 1 << 20;
    char* buf = (char*)malloc(BUF);
    long long rows = 0;
    size_t got;
    char last = '\n';
    while ((got = fread(buf, 1, BUF, f)) > 0) {
        for (size_t i = 0; i < got; i++)
            if (buf[i] == '\n') rows++;
        last = buf[got - 1];
    }
    if (last != '\n') rows++;   // unterminated final line
    free(buf);
    fclose(f);
    return rows;
}

// Parse the whole file.  outs[i] points at the i-th column's buffer.
// kinds[i]: see header comment.  scales[i]: decimal scale or text width.
// Returns rows parsed, or -(line_number) on a malformed line.
long long otb_parse(const char* path, char delim, int ncols,
                    const int* kinds, const int* scales,
                    void** outs, long long max_rows) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    // read whole file (bulk load: file sizes are what RAM holds anyway)
    fseek(f, 0, SEEK_END);
    long long fsize = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* data = (char*)malloc((size_t)fsize + 2);
    if (!data) { fclose(f); return -2; }
    if (fread(data, 1, (size_t)fsize, f) != (size_t)fsize) {
        free(data); fclose(f); return -3;
    }
    fclose(f);
    if (fsize == 0 || data[fsize - 1] != '\n') data[fsize++] = '\n';
    data[fsize] = '\0';

    long long row = 0;
    char* p = data;
    char* end = data + fsize;
    while (p < end && row < max_rows) {
        if (*p == '\n') { p++; continue; }   // skip blank lines
        for (int c = 0; c < ncols; c++) {
            char* fieldEnd = p;
            while (fieldEnd < end && *fieldEnd != delim &&
                   *fieldEnd != '\n') fieldEnd++;
            if (memchr(p, '\\', (size_t)(fieldEnd - p))) {
                // backslash: \N NULL marker or escaped text (the COPY
                // text format) — this fast path is NULL/escape-free;
                // refuse so the caller uses the general loader
                free(data);
                return -4;
            }
            switch (kinds[c]) {
            case 0: {   // int64
                ((int64_t*)outs[c])[row] = strtoll(p, nullptr, 10);
                break;
            }
            case 1: {   // float64
                ((double*)outs[c])[row] = strtod(p, nullptr);
                break;
            }
            case 2: {   // decimal -> scaled int64 (exact, no fp round)
                int64_t sign = 1;
                char* q = p;
                if (*q == '-') { sign = -1; q++; }
                else if (*q == '+') q++;
                int64_t whole = 0;
                while (q < fieldEnd && *q >= '0' && *q <= '9')
                    whole = whole * 10 + (*q++ - '0');
                int64_t frac = 0;
                int fd = 0;
                int scale = scales[c];
                if (q < fieldEnd && *q == '.') {
                    q++;
                    while (q < fieldEnd && *q >= '0' && *q <= '9') {
                        if (fd < scale) { frac = frac * 10 + (*q - '0');
                                          fd++; }
                        q++;
                    }
                }
                while (fd < scale) { frac *= 10; fd++; }
                int64_t mult = 1;
                for (int s = 0; s < scale; s++) mult *= 10;
                ((int64_t*)outs[c])[row] = sign * (whole * mult + frac);
                break;
            }
            case 3: {   // date YYYY-MM-DD -> int32 days
                long y = strtol(p, nullptr, 10);
                long m = strtol(p + 5, nullptr, 10);
                long d = strtol(p + 8, nullptr, 10);
                ((int32_t*)outs[c])[row] =
                    (int32_t)days_from_civil(y, m, d);
                break;
            }
            case 4: {   // text -> fixed width bytes (null padded)
                int w = scales[c];
                int n = (int)(fieldEnd - p);
                if (n > w) {      // over-length: refuse (caller falls
                    free(data);   // back to the general loader)
                    return -(row + 100000);
                }
                char* dst = (char*)outs[c] + (size_t)row * w;
                memcpy(dst, p, n);
                if (n < w) memset(dst + n, 0, w - n);
                break;
            }
            case 5: {   // bool: t/f/true/false/1/0
                ((int64_t*)outs[c])[row] =
                    (*p == 't' || *p == 'T' || *p == '1') ? 1 : 0;
                break;
            }
            default:
                free(data);
                return -(row + 10);
            }
            p = fieldEnd;
            if (p < end && *p == delim) p++;
        }
        // skip trailing delimiter + newline
        while (p < end && *p != '\n') p++;
        p++;
        row++;
    }
    free(data);
    return row;
}

}  // extern "C"
