"""Benchmark driver: TPC-H through the engine on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
- value: Q1 throughput in Mrows/s of lineitem scanned (engine device path)
- vs_baseline: speedup over the CPU control arm (pandas, BASELINE.md's
  "CPU DataNode" stand-in) on the same machine & data
- tpu_unavailable: true when the axon tunnel was down and the run fell
  back to CPU (the number is then NOT a TPU measurement)

Modes via env:
- BENCH_SF (default 1.0), BENCH_REPEAT (default 5)
- BENCH_MODE=single (default): single-node Q1 through the fused engine
- BENCH_MODE=mesh: distributed Q1 over an in-process cluster whose
  datanode fragments + exchanges run as ONE shard_map program per query
  on a mesh of all visible devices (exec/mesh_exec.py)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The package probes the tunnel at import; give the bench a longer budget
# than the library default (must be set before the import below).
os.environ.setdefault("OTB_TPU_PROBE_TIMEOUT", "90")

from opentenbase_tpu.utils.backend import ensure_alive_backend  # noqa: E402

requested_tpu = os.environ.get("JAX_PLATFORMS", "") != "cpu"
platform = ensure_alive_backend(timeout_s=90)
tpu_unavailable = requested_tpu and platform == "cpu"

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _pandas_q1(tbl, repeat):
    import pandas as pd
    li = pd.DataFrame({k: tbl[k] for k in
                       ("l_returnflag", "l_linestatus", "l_quantity",
                        "l_extendedprice", "l_discount", "l_tax",
                        "l_shipdate")})
    cutoff = 10471  # 1998-09-02
    ptimes = []
    for _ in range(max(2, repeat // 2)):
        t2 = time.perf_counter()
        df = li[li.l_shipdate <= cutoff]
        dp = df.l_extendedprice * (1 - df.l_discount)
        ch = dp * (1 + df.l_tax)
        df.assign(dp=dp, ch=ch).groupby(
            ["l_returnflag", "l_linestatus"]).agg(
            sq=("l_quantity", "sum"), sp=("l_extendedprice", "sum"),
            sdp=("dp", "sum"), sch=("ch", "sum"),
            aq=("l_quantity", "mean"), ap=("l_extendedprice", "mean"),
            ad=("l_discount", "mean"), n=("l_quantity", "count"))
        ptimes.append(time.perf_counter() - t2)
    return min(ptimes)


def main():
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeat = int(os.environ.get("BENCH_REPEAT", "5"))
    mode = os.environ.get("BENCH_MODE", "single")

    from opentenbase_tpu.tpch import datagen
    from opentenbase_tpu.tpch.queries import Q
    from opentenbase_tpu.tpch.schema import SCHEMA

    t0 = time.time()
    data = datagen.generate(sf=sf)
    tbl = data["lineitem"]
    n_rows = len(tbl["l_orderkey"])

    if mode == "mesh":
        from opentenbase_tpu.exec.dist_session import ClusterSession
        from opentenbase_tpu.parallel.cluster import Cluster
        ndn = max(len(jax.devices()), 1)
        s = ClusterSession(Cluster(n_datanodes=ndn))
        s.execute(SCHEMA)
        td = s.cluster.catalog.table("lineitem")
        s._insert_rows(td, tbl, n_rows)
        s.execute("set enable_mesh_exchange = on")
        run = lambda: s.query(Q[1])
        label = f"mesh x{ndn}"
    else:
        from opentenbase_tpu.exec.session import LocalNode, Session
        node = LocalNode()
        s = Session(node)
        s.execute(SCHEMA)
        td = node.catalog.table("lineitem")
        st = node.stores["lineitem"]
        s._insert_rows(td, st, tbl, n_rows)
        run = lambda: s.query(Q[1])
        label = "single"
    gen_s = time.time() - t0

    run()  # warm (compile + device staging)
    times = []
    for _ in range(repeat):
        t1 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t1)
    engine_s = min(times)

    pandas_s = _pandas_q1(tbl, repeat)

    mrows = n_rows / engine_s / 1e6
    out = {
        "metric": f"TPC-H Q1 SF{sf:g} throughput ({platform}, {label})",
        "value": round(mrows, 3),
        "unit": "Mrows/s",
        "vs_baseline": round(pandas_s / engine_s, 3),
    }
    if tpu_unavailable:
        out["tpu_unavailable"] = True
    print(json.dumps(out))
    print(f"# rows={n_rows} engine={engine_s*1e3:.1f}ms "
          f"pandas={pandas_s*1e3:.1f}ms datagen={gen_s:.1f}s "
          f"platform={platform} mode={mode}", file=sys.stderr)


if __name__ == "__main__":
    main()
