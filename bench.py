"""Benchmark driver: TPC-H Q1 through the full SQL engine on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
- value: Q1 throughput in Mrows/s of lineitem scanned (engine, device path)
- vs_baseline: speedup over the CPU control arm (pandas, BASELINE.md's
  "CPU DataNode" stand-in) on the same machine & data

Scale via env: BENCH_SF (default 1.0), BENCH_REPEAT (default 5).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from opentenbase_tpu.utils.backend import ensure_alive_backend  # noqa: E402

platform = ensure_alive_backend(timeout_s=90)

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main():
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeat = int(os.environ.get("BENCH_REPEAT", "5"))

    from opentenbase_tpu.exec.session import LocalNode, Session
    from opentenbase_tpu.tpch import datagen
    from opentenbase_tpu.tpch.queries import Q
    from opentenbase_tpu.tpch.schema import SCHEMA

    t0 = time.time()
    data = datagen.generate(sf=sf)
    node = LocalNode()
    s = Session(node)
    s.execute(SCHEMA)
    # bench loads only what Q1 needs (lineitem)
    td = node.catalog.table("lineitem")
    st = node.stores["lineitem"]
    tbl = data["lineitem"]
    n_rows = len(tbl["l_orderkey"])
    s._insert_rows(td, st, tbl, n_rows)
    gen_s = time.time() - t0

    # warm (compile + device staging)
    s.query(Q[1])
    times = []
    for _ in range(repeat):
        t1 = time.perf_counter()
        s.query(Q[1])
        times.append(time.perf_counter() - t1)
    engine_s = min(times)

    # CPU control arm: pandas (the classic CPU DataNode stand-in)
    import pandas as pd
    li = pd.DataFrame({k: tbl[k] for k in
                       ("l_returnflag", "l_linestatus", "l_quantity",
                        "l_extendedprice", "l_discount", "l_tax",
                        "l_shipdate")})
    cutoff = 10471  # 1998-09-02
    ptimes = []
    for _ in range(max(2, repeat // 2)):
        t2 = time.perf_counter()
        df = li[li.l_shipdate <= cutoff]
        dp = df.l_extendedprice * (1 - df.l_discount)
        ch = dp * (1 + df.l_tax)
        df.assign(dp=dp, ch=ch).groupby(
            ["l_returnflag", "l_linestatus"]).agg(
            sq=("l_quantity", "sum"), sp=("l_extendedprice", "sum"),
            sdp=("dp", "sum"), sch=("ch", "sum"),
            aq=("l_quantity", "mean"), ap=("l_extendedprice", "mean"),
            ad=("l_discount", "mean"), n=("l_quantity", "count"))
        ptimes.append(time.perf_counter() - t2)
    pandas_s = min(ptimes)

    mrows = n_rows / engine_s / 1e6
    print(json.dumps({
        "metric": f"TPC-H Q1 SF{sf:g} throughput ({platform})",
        "value": round(mrows, 3),
        "unit": "Mrows/s",
        "vs_baseline": round(pandas_s / engine_s, 3),
    }))
    print(f"# rows={n_rows} engine={engine_s*1e3:.1f}ms "
          f"pandas={pandas_s*1e3:.1f}ms datagen={gen_s:.1f}s "
          f"platform={platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
