"""Benchmark driver: the BASELINE.md measurement ladder through the engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "ladder"}.
- headline value: TPC-H Q1 throughput in Mrows/s of lineitem scanned on the
  device-mesh data plane (or single-node fused when only one config runs)
- vs_baseline: speedup over the CPU control arm (pandas, BASELINE.md's
  "CPU DataNode" stand-in) on the same machine & data
- ladder: per-config results — Q1/Q3/Q5 single-node fused (BASELINE
  config 1; Q3/Q5 run as fused JOIN fragments — late-materialized
  index-composition joins in one XLA program) plus Q1/Q3/Q5 through
  the mesh tier (config 2: joins + all_to_all redistribution as ONE
  shard_map program per query).  Every query entry reports the
  late-materialization counters (mat_deferred_cols / mat_eager_cols /
  mat_cols_gathered / mat_bytes_gathered / join_host_syncs) for its
  timed runs.  Mesh entries
  split a warm repeat into stage_ms (host->device upload; ~0 when the
  device buffer pool serves every table resident) vs compute_ms, and
  report the pool hit rate + bytes staged on that repeat
  (storage/bufferpool.py — engine_ms stays the min-of-warm-runs number
  comparable to earlier rounds), plus the compressed-residency block
  (storage/codec.py): bytes_logical / bytes_resident /
  effective_cache_ratio of the live pool
- tpu_unavailable: true when the axon tunnel was down and the run fell
  back to CPU (the numbers are then NOT TPU measurements)

Modes via env:
- BENCH_SF (default 1.0), BENCH_REPEAT (default 5)
- BENCH_MODE=ladder (default) | single | mesh — single/mesh run only that
  one arm (the r1/r2 behavior) for quick checks
- BENCH_MODE=qps: the serving-tier arm (exec/scheduler.py) — sustained
  throughput with 8/64/256 concurrent clients over (a) a same-signature
  point-SELECT workload (varying key literal: every query is the SAME
  literal-masked compiled program, so the scheduler coalesces them into
  multi-query dispatches and amortizes per-query host overhead), (b) a
  same-signature analytics workload (Q1 with a varying shipdate
  literal), and (c) a mixed Q1/Q3/Q5 + point-SELECT workload.
  Reports per-arm qps, p50/p99 latency, batch_rate
  (fraction of admitted queries served by a multi-query dispatch), shed
  count, and the dispatch-size histogram, plus a single-session
  serial-loop baseline per workload.  Knobs: BENCH_QPS_SECONDS (timed
  window per arm, default 4), BENCH_QPS_WARM_SECONDS (untimed
  compile-warm phase per arm, default 2), BENCH_QPS_CLIENTS (default
  "8,64,256"), BENCH_QPS_BASELINE_N (serial baseline queries, default
  60); BENCH_SF defaults to 0.05 in this mode.  A zipf_cache arm per
  client count drives zipfian-skewed repeated statements through the
  GTS-versioned result cache (exec/share.py): device dispatches stay
  near the distinct-statement count while served queries scale with
  clients, every response verified (knobs: BENCH_QPS_ZIPF_DISTINCT
  default 48, BENCH_QPS_ZIPF_SKEW default 1.2)
- BENCH_OLTP=1: additionally measure the point-op latency path (FQS
  INSERT/SELECT p50) — the reference's execLight.c OLTP story
- --trace: after each timed arm, dump the full last-query span tree
  (obs/trace.py) as one JSON line on stderr; every ladder entry also
  carries a "phases" breakdown (plan/stage/execute/exchange/finalize
  ms of the arm's last warm run), and the final JSON gains a
  "latency" block with p50/p95/p99 per tier from the unified metrics
  registry's otb_query_ms histograms (obs/metrics.py)
- BENCH_WARM2=1 (default): the warm-restart arm — after the ladder, a
  FRESH python process re-runs Q1/Q3/Q5 against the persistent XLA
  compilation cache the first run populated (exec/plancache.py), and
  its first-query cold_ms rides into the ladder as warm2_ms.  This is
  the restart story: round 5 paid 11-12s of compile per cold mesh
  query; with the cache the second process should land near engine_ms.
- OTB_COMPILE_CACHE: persistent cache dir (default: a fresh temp dir,
  shared with the warm2 child)
- --chaos: SKIP the ladder; instead run point reads against a live
  TCP cluster while one DN flaps (wire-level close faults) and print
  p50/p99 latency, error rate, wrong-result count, and the otbguard
  counters (net/guard.py).  Knobs: BENCH_CHAOS_OPS (400),
  BENCH_CHAOS_FLAP_EVERY (50), plus the OTB_RPC_*/OTB_BREAKER_* envs.
- --chaos-concurrent: the otbshield acceptance arm — 64 client threads
  (coalescing scheduler + a flapping TCP cluster) under simultaneous
  poisoned-literal, cancel-storm, dispatch-OOM, wire-flap, and shed
  pressure.  ONE JSON line with qps, p50/p99, the offender-vs-
  collateral error split (collateral must be 0), wrong_results (must
  be 0), degraded count, and the admission-slot + GTM-lease ledgers
  (must balance); exits nonzero when any acceptance number fails.
  Knobs: BENCH_CHAOSC_SECONDS (8), BENCH_CHAOSC_WARM_SECONDS (2),
  BENCH_CHAOSC_CLIENTS (64), BENCH_CHAOSC_SF (0.02),
  BENCH_CHAOSC_ANALYTICS=0 for a quick smoke run.
- --oob: the out-of-core arm (exec/morsel.py) — SKIP the ladder; cap
  OTB_DEVICE_CACHE_BYTES at what the BENCH_OOB_CAP_SF (default 1)
  dataset would occupy staged, then run Q1/Q3/Q5 at BENCH_OOB_SF
  (default 10) through the morsel streaming tier.  ONE JSON line with
  per-query GB/s of bytes touched (vs the uncapped in-memory run),
  chunk count, chunk_downshifts, bytes_streamed, bit_identical, and
  warm_programs_compiled (must be 0 — chunk count never reaches a
  program key), plus the bufferpool pin ledger (must balance).  Each
  query also reports compressed residency (bytes_logical /
  bytes_resident / effective_cache_ratio; effective_cache_x =
  min over queries, acceptance floor 2.5x) and a codec-off control
  (OTB_CODEC=0: raw_ms, gb_per_s_raw, x_codec_off,
  bit_identical_codec_off — encoded execution must match raw
  byte-for-byte).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The package probes the tunnel at import; give the bench a longer budget
# than the library default (must be set before the import below).
os.environ.setdefault("OTB_TPU_PROBE_TIMEOUT", "90")

from opentenbase_tpu.utils.backend import ensure_alive_backend  # noqa: E402

requested_tpu = os.environ.get("JAX_PLATFORMS", "") != "cpu"
platform = ensure_alive_backend(timeout_s=90)
tpu_unavailable = requested_tpu and platform == "cpu"

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _d(iso):
    return int((np.datetime64(iso, "D")
                - np.datetime64("1970-01-01", "D")).astype(np.int64))


def _pandas_q1(dfs):
    li = dfs["lineitem"]
    df = li[li.l_shipdate <= _d("1998-09-02")]
    dp = df.l_extendedprice * (1 - df.l_discount)
    ch = dp * (1 + df.l_tax)
    df.assign(dp=dp, ch=ch).groupby(
        ["l_returnflag", "l_linestatus"]).agg(
        sq=("l_quantity", "sum"), sp=("l_extendedprice", "sum"),
        sdp=("dp", "sum"), sch=("ch", "sum"),
        aq=("l_quantity", "mean"), ap=("l_extendedprice", "mean"),
        ad=("l_discount", "mean"), n=("l_quantity", "count"))


def _pandas_q3(dfs):
    c, o, li = dfs["customer"], dfs["orders"], dfs["lineitem"]
    df = c[c.c_mktsegment == "BUILDING"].merge(
        o, left_on="c_custkey", right_on="o_custkey")
    df = df[df.o_orderdate < _d("1995-03-15")]
    df = df.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    df = df[df.l_shipdate > _d("1995-03-15")]
    df = df.assign(rev=df.l_extendedprice * (1 - df.l_discount))
    df.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])[
        "rev"].sum().reset_index().sort_values(
        ["rev", "o_orderdate"], ascending=[False, True]).head(10)


def _pandas_q5(dfs):
    t = dfs
    df = t["customer"].merge(t["orders"], left_on="c_custkey",
                             right_on="o_custkey")
    df = df.merge(t["lineitem"], left_on="o_orderkey",
                  right_on="l_orderkey")
    df = df.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    df = df[df.c_nationkey == df.s_nationkey]
    df = df.merge(t["nation"], left_on="s_nationkey",
                  right_on="n_nationkey")
    df = df.merge(t["region"], left_on="n_regionkey",
                  right_on="r_regionkey")
    df = df[(df.r_name == "ASIA") & (df.o_orderdate >= _d("1994-01-01"))
            & (df.o_orderdate < _d("1995-01-01"))]
    df.assign(rev=df.l_extendedprice * (1 - df.l_discount)).groupby(
        "n_name")["rev"].sum().reset_index().sort_values(
        "rev", ascending=False)


# columns each query actually touches (8 bytes/value storage) — the
# bytes-touched estimate under perfect column pruning
_Q_COLS = {
    1: {"lineitem": 7},                       # shipdate,qty,price,disc,tax,rf,ls
    3: {"lineitem": 4, "orders": 4, "customer": 2},
    5: {"lineitem": 4, "orders": 3, "customer": 2, "supplier": 2,
        "nation": 3, "region": 2},
}


def _gb_touched(qn, data):
    total = 0
    for t, ncols in _Q_COLS.get(qn, {}).items():
        rows = len(next(iter(data[t].values())))
        total += rows * ncols * 8
    return total / 1e9


def _time(fn, repeat):
    """(best_warm_s, cold_s): cold = first run including compile +
    staging — the interactive first-query cost min() alone hides
    (VERDICT r4 weak #8)."""
    t0 = time.perf_counter()
    fn()  # cold (compile + staging)
    cold = time.perf_counter() - t0
    times = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times), cold


def _oltp_latencies(s, n=200):
    """Point-op p50 (ms): single-shard INSERT, raw-literal SELECT (replan
    + recompile per value), and PREPAREd SELECT (plan cache + light
    coordinator — the execLight.c OLTP fast path)."""
    s.execute("create table if not exists bench_kv (k bigint primary key, "
              "v bigint) distribute by shard(k)")
    s.execute("prepare __bget (bigint) as "
              "select v from bench_kv where k = $1")
    s.execute("prepare __bins (bigint, bigint) as "
              "insert into bench_kv values ($1, $2)")
    ins, raw, prep = [], [], []
    for i in range(n):
        t0 = time.perf_counter()
        s.execute(f"execute __bins ({i}, {i * 7})")
        ins.append(time.perf_counter() - t0)
        if i < 30:   # the slow arm: cap its share of bench wall-clock
            t0 = time.perf_counter()
            s.query(f"select v from bench_kv where k = {i}")
            raw.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        s.query(f"execute __bget ({i})")
        prep.append(time.perf_counter() - t0)
    return (float(np.median(ins) * 1e3), float(np.median(raw) * 1e3),
            float(np.median(prep) * 1e3))


TRACE_DUMP = "--trace" in sys.argv[1:]
CHAOS = "--chaos" in sys.argv[1:]
CHAOS_CONCURRENT = "--chaos-concurrent" in sys.argv[1:]
OOB = "--oob" in sys.argv[1:]


def _oob_arm():
    """--oob: the out-of-core acceptance arm (exec/morsel.py) — SF10 on
    an SF1 device budget.  OTB_DEVICE_CACHE_BYTES is capped at what the
    cap-SF dataset would occupy staged (the "SF1 device"), then
    Q1/Q3/Q5 run at BENCH_OOB_SF through the morsel tier: the dominant
    scan streams in fixed-shape pinned chunks, blocking operators
    decompose per chunk, and the answer must be bit-identical to the
    uncapped in-memory run.  Prints ONE JSON line; per query it
    reports gb_touched / gb_per_s (bytes-touched throughput, the
    out-of-core figure of merit vs gb_per_s_in_memory), chunk count,
    chunk_downshifts, bytes_streamed, bit_identical, and
    warm_programs_compiled (MUST be 0: chunk count/offsets never reach
    a program key, so a warm stream recompiles nothing).  Each query
    also carries the compressed-residency block (storage/codec.py):
    bytes_logical / bytes_resident / effective_cache_ratio of the live
    pool after the streamed run, plus a codec-off control arm
    (OTB_CODEC=0, raw residency, SAME streamed query) reporting
    raw_ms / gb_per_s_raw / x_codec_off (the GB/s-touched delta the
    codecs buy) and bit_identical_codec_off (encoded execution must
    return byte-for-byte the raw arm's rows).  Knobs:
    BENCH_OOB_SF (default 10), BENCH_OOB_CAP_SF (default 1),
    BENCH_REPEAT (default 3) — smoke runs use e.g. BENCH_OOB_SF=0.2
    BENCH_OOB_CAP_SF=0.02."""
    from opentenbase_tpu.exec import morsel as morsel_mod
    from opentenbase_tpu.exec.session import LocalNode, Session
    from opentenbase_tpu.storage import codec
    from opentenbase_tpu.storage.batch import size_class
    from opentenbase_tpu.storage.bufferpool import POOL
    from opentenbase_tpu.tpch import datagen
    from opentenbase_tpu.tpch.queries import Q
    from opentenbase_tpu.tpch.schema import SCHEMA

    sf = float(os.environ.get("BENCH_OOB_SF", "10"))
    cap_sf = float(os.environ.get("BENCH_OOB_CAP_SF", "1"))
    repeat = max(1, int(os.environ.get("BENCH_REPEAT", "3")))

    t0 = time.time()
    data = datagen.generate(sf=sf)
    gen_s = time.time() - t0
    n_rows = len(data["lineitem"]["l_orderkey"])

    # the SF-cap device budget: what the FULL cap-SF dataset would
    # occupy staged (value + MVCC sys columns, size_class padding) —
    # a device sized to hold SF1 resident, which SF10 streams through
    cap = 0
    for cols in data.values():
        rows = len(next(iter(cols.values())))
        cap += size_class(max(int(rows * cap_sf / sf), 1)) \
            * (len(cols) + 4) * 8
    os.environ["OTB_DEVICE_CACHE_BYTES"] = str(cap)

    node = LocalNode()
    s = Session(node)
    s.execute(SCHEMA)
    for tname in ("region", "nation", "supplier", "customer",
                  "orders", "lineitem"):
        td = node.catalog.table(tname)
        nn = len(next(iter(data[tname].values())))
        s._insert_rows(td, node.stores[tname], data[tname], nn)

    ladder = []
    for qn in (1, 3, 5):
        # uncapped in-memory truth + timing (the comparison arm)
        s.execute("set morsel = off")
        ref = s.query(Q[qn])
        eng_mem, _ = _time(lambda: s.query(Q[qn]),
                           max(1, repeat // 2))
        # the streamed arm: auto-activation under the capped budget
        s.execute("set morsel = auto")
        POOL.clear()
        m0 = morsel_mod.stats_snapshot()
        c0 = _compile_snapshot()
        t1 = time.perf_counter()
        got = s.query(Q[qn])
        cold = time.perf_counter() - t1
        c1 = _compile_snapshot()
        times = []
        for _ in range(repeat):
            t1 = time.perf_counter()
            s.query(Q[qn])
            times.append(time.perf_counter() - t1)
        c2 = _compile_snapshot()
        m1 = morsel_mod.stats_snapshot()
        eng = min(times)
        gb = _gb_touched(qn, data)
        res = _residency_block()
        pool_snap = POOL.totals()

        # codec-off control: the SAME streamed query with OTB_CODEC=0
        # (raw device residency) — encoded execution must be
        # bit-identical, and the GB/s-touched delta is what compressed
        # residency buys end to end under the same cap
        codec_env = os.environ.get("OTB_CODEC")
        os.environ["OTB_CODEC"] = "0"
        codec.reset_state()
        POOL.clear()
        try:
            got_raw = s.query(Q[qn])
            raw_times = []
            for _ in range(max(1, repeat // 2)):
                t1 = time.perf_counter()
                s.query(Q[qn])
                raw_times.append(time.perf_counter() - t1)
            eng_raw = min(raw_times)
        finally:
            if codec_env is None:
                os.environ.pop("OTB_CODEC", None)
            else:
                os.environ["OTB_CODEC"] = codec_env
            codec.reset_state()
            POOL.clear()

        entry = {"config": f"Q{qn} oob SF{sf:g}",
                 "engine_ms": eng * 1e3, "cold_ms": cold * 1e3,
                 "in_memory_ms": eng_mem * 1e3,
                 "x_in_memory": eng / eng_mem,
                 "gb_touched": gb, "gb_per_s": gb / eng,
                 "gb_per_s_in_memory": gb / eng_mem,
                 "streamed": m1["streams"] - m0["streams"] > 0,
                 "chunks": m1["chunks"] - m0["chunks"],
                 "chunk_downshifts": m1["chunk_downshifts"]
                 - m0["chunk_downshifts"],
                 "bytes_streamed": m1["bytes_streamed"]
                 - m0["bytes_streamed"],
                 "bit_identical": _rows_close(got, ref),
                 "warm_programs_compiled": c2[0] - c1[0],
                 **res,
                 "raw_ms": eng_raw * 1e3,
                 "gb_per_s_raw": gb / eng_raw,
                 "x_codec_off": eng_raw / eng,
                 "bit_identical_codec_off": got == got_raw}
        entry.update(_compile_counters(c0, c1))
        ladder.append(entry)
        s.execute("set morsel = off")

    head = ladder[0]
    # the codec-off control clears the pool; report the LAST encoded
    # run's live-pool numbers, not the post-clear zeros
    pool = pool_snap
    out = {
        "metric": f"out-of-core Q1 SF{sf:g} bytes-touched throughput "
                  f"(SF{cap_sf:g}-sized device cache, {platform})",
        "value": round(head["gb_per_s"], 3),
        "unit": "GB/s",
        "vs_baseline": round(head["gb_per_s"]
                             / head["gb_per_s_in_memory"], 3)
        if head["gb_per_s_in_memory"] else 0.0,
        "device_cache_bytes": cap,
        # compressed residency: the effective device-cache multiplier
        # (min over Q1/Q3/Q5 — the acceptance floor is >= 2.5x)
        "effective_cache_x": round(
            min(e["effective_cache_ratio"] for e in ladder), 3),
        "ladder": [{k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in e.items()} for e in ladder],
        "pin_ledger": POOL.check_pin_ledger(),
        "pool": {k: pool[k] for k in ("bytes_live", "chunks_live",
                                      "evictions", "uploaded_bytes")},
    }
    if tpu_unavailable:
        out["tpu_unavailable"] = True
    print(json.dumps(out))
    print(f"# oob: sf={sf} cap_sf={cap_sf} cap={cap} rows={n_rows} "
          f"datagen={gen_s:.1f}s platform={platform}", file=sys.stderr)


def _chaos_arm():
    """--chaos: point reads against a live TCP cluster while one DN
    flaps — wire-level close faults (utils/faultinject.py) tear dn0's
    conversations every BENCH_CHAOS_FLAP_EVERY ops.  Prints ONE JSON
    line: p50/p99 latency, error rate, wrong-result count (must be 0:
    a retried or failed read may error but never lie), and the
    otbguard counters (retries, breaker trips, half-open recoveries)
    — the ISSUE-8 acceptance numbers under sustained flapping."""
    import shutil
    from opentenbase_tpu.exec.dist_session import ClusterSession
    from opentenbase_tpu.gtm.server import GtmCore, GtmServer
    from opentenbase_tpu.obs.metrics import REGISTRY
    from opentenbase_tpu.net.dn_server import DnServer
    from opentenbase_tpu.parallel.cluster import Cluster
    from opentenbase_tpu.utils import faultinject as FI

    n_ops = int(os.environ.get("BENCH_CHAOS_OPS", "400"))
    flap_every = int(os.environ.get("BENCH_CHAOS_FLAP_EVERY", "50"))
    # fast breaker so trips AND half-open recoveries land inside the
    # run (production defaults are read per-call from the same knobs)
    os.environ.setdefault("OTB_BREAKER_THRESHOLD", "3")
    os.environ.setdefault("OTB_BREAKER_COOLDOWN", "0.2")
    os.environ.setdefault("OTB_RPC_RETRIES", "2")

    d = tempfile.mkdtemp(prefix="otb-chaos-")
    Cluster(n_datanodes=2, datadir=d).checkpoint()
    gtm = GtmServer(GtmCore(os.path.join(d, "gtm.json"))).start()
    catalog_path = os.path.join(d, "catalog.json")
    servers = [DnServer(i, os.path.join(d, f"dn{i}"), catalog_path,
                        gtm_addr=(gtm.host, gtm.port)).start()
               for i in range(2)]
    cluster = Cluster.connect(catalog_path,
                              [(s.host, s.port) for s in servers],
                              (gtm.host, gtm.port))
    try:
        s = ClusterSession(cluster)
        s.execute("create table chaos_kv (k bigint primary key, "
                  "v bigint) distribute by shard(k)")
        s.execute("insert into chaos_kv values " + ", ".join(
            f"({i}, {i * 3})" for i in range(64)))

        lat, errors, wrong = [], 0, 0
        wv0 = _wait_snapshot()
        t_all = time.perf_counter()
        for i in range(n_ops):
            if i and i % flap_every == 0:
                # flap dn0: tear its next 6 wire conversations —
                # enough failed attempts to trip the breaker through
                # the retry budget, then let it half-open-recover
                FI.arm_wire("dn0.recv", "close", times=6)
            k = i % 64
            t0 = time.perf_counter()
            try:
                rows = s.query(f"select v from chaos_kv where k = {k}")
                if rows != [(k * 3,)]:
                    wrong += 1
            except Exception:   # noqa: BLE001 — the error rate IS the metric
                errors += 1
            lat.append(time.perf_counter() - t0)
        wall_s = time.perf_counter() - t_all
        FI.disarm_wire()

        counters = {}
        for name, labels, kind, value in REGISTRY.samples():
            if kind == "counter" and name.startswith("otb_guard_"):
                counters[name] = counters.get(name, 0) + int(value)

        # flight-recorder smoke: the flapping DN tripped the breaker,
        # so at least one postmortem bundle must exist AND round-trip
        # through JSON — a chaos run that leaves no forensics is a
        # regression in the recorder, not a quiet success
        from opentenbase_tpu.obs import xray
        bundles = xray.flights()
        assert bundles, "DN flap produced no flight bundle"
        for b in bundles:
            json.loads(json.dumps(b))

        ms = np.asarray(lat) * 1e3
        out = {
            "metric": "chaos point-read p99 (one DN flapping)",
            "value": round(float(np.percentile(ms, 99)), 3),
            "unit": "ms",
            "ops": n_ops,
            "wall_s": round(wall_s, 2),
            "p50_ms": round(float(np.percentile(ms, 50)), 3),
            "p99_ms": round(float(np.percentile(ms, 99)), 3),
            "error_rate": round(errors / n_ops, 4),
            "wrong_results": wrong,
            "guard_counters": dict(sorted(counters.items())),
            "flight_bundles": len(bundles),
            "wait_events": _wait_block(wv0),
        }
        if tpu_unavailable:
            out["tpu_unavailable"] = True
        print(json.dumps(out))
    finally:
        FI.disarm_wire()
        res = getattr(cluster, "_resolver", None)
        if res is not None:
            res.stop()
        for srv in servers:
            try:
                srv.stop()
            except Exception:   # noqa: BLE001 — best-effort teardown
                pass
        gtm.stop()
        shutil.rmtree(d, ignore_errors=True)


def _rows_close(got, want):
    """Wrong-result check: exact for ints/strings, tight relative
    tolerance for floats (a degraded/spill re-execution may legally
    re-associate float reductions; it may never change an answer)."""
    if got == want:
        return True
    if got is None or want is None or len(got) != len(want):
        return False
    for rg, rw in zip(got, want):
        if len(rg) != len(rw):
            return False
        for a, b in zip(rg, rw):
            if isinstance(a, float) or isinstance(b, float):
                if abs(float(a) - float(b)) > 1e-6 * max(
                        1.0, abs(float(b))):
                    return False
            elif a != b:
                return False
    return True


def _snap_certificate():
    """Post-hoc otbsnap certificate for the current process: run the
    Adya G1/G-SI checker (analysis/sicheck.py) over the in-memory
    snapcheck history, persist the history to $OTB_SNAP_HISTORY, and
    report the runtime sanitizer's violation count.  The bench gates on
    si_anomalies == 0 and snapcheck_violations == 0 — the three
    serving tiers (cache / replica / shared) certified against the
    commit history they actually raced."""
    from opentenbase_tpu.analysis import sicheck
    from opentenbase_tpu.utils import snapcheck
    res = sicheck.check_history(snapcheck.history_events())
    if snapcheck.history_on():
        snapcheck.save_history()
    return {"si_anomalies": len(res["anomalies"]),
            "si_reads": res["reads"], "si_writes": res["writes"],
            "si_by_source": res["by_source"],
            "snapcheck_violations": len(snapcheck.violations()),
            "si_detail": res["anomalies"][:5]}


def _chaosc_streams(analytics):
    """The mixed chaos workload: point SELECTs (one tiny coalescable
    signature), a small-agg signature, and — unless disabled for smoke
    runs — the Q1-varying-literal / Q3 / Q5 analytics shapes from the
    qps arm.  Key 251 is reserved for the poison offender's stream and
    never appears in a clean literal."""
    points = [f"select v from qps_kv where k = {(i * 37) % 250}"
              for i in range(64)]
    aggs = [f"select sum(v), count(*) from qps_kv where k < {60 + 7 * i}"
            for i in range(8)]
    mixed = []
    if analytics:
        _, same, _ = _qps_queries()
        from opentenbase_tpu.tpch.queries import Q
        for i in range(16):
            mixed.append(points[i % len(points)])
            mixed.append(same[i % len(same)])
            mixed.append(aggs[i % len(aggs)])
            if i % 5 == 0:
                mixed.append(Q[3])
            if i % 8 == 0:
                mixed.append(Q[5])
            mixed.append(points[(i * 3 + 1) % len(points)])
    else:
        for i in range(16):
            mixed.append(points[i % len(points)])
            mixed.append(aggs[i % len(aggs)])
            mixed.append(points[(i * 3 + 1) % len(points)])
    return mixed


def _chaosc_flap_cluster(tmp):
    """Plane B of --chaos-concurrent: a live 2-DN TCP cluster whose
    dn0 wire will flap mid-run.  Gentle knobs — the retry budget must
    absorb every tear (times=2 faults < 3 retries, breaker threshold
    high enough to never fast-fail): errors here are COLLATERAL."""
    from opentenbase_tpu.exec.dist_session import ClusterSession
    from opentenbase_tpu.gtm.server import GtmCore, GtmServer
    from opentenbase_tpu.net.dn_server import DnServer
    from opentenbase_tpu.parallel.cluster import Cluster

    os.environ.setdefault("OTB_RPC_RETRIES", "3")
    os.environ.setdefault("OTB_BREAKER_THRESHOLD", "16")
    Cluster(n_datanodes=2, datadir=tmp).checkpoint()
    gtm = GtmServer(GtmCore(os.path.join(tmp, "gtm.json"))).start()
    catalog_path = os.path.join(tmp, "catalog.json")
    servers = [DnServer(i, os.path.join(tmp, f"dn{i}"), catalog_path,
                        gtm_addr=(gtm.host, gtm.port)).start()
               for i in range(2)]
    cluster = Cluster.connect(catalog_path,
                              [(s.host, s.port) for s in servers],
                              (gtm.host, gtm.port))
    s = ClusterSession(cluster)
    s.execute("create table chaos_kv (k bigint primary key, v bigint) "
              "distribute by shard(k)")
    s.execute("insert into chaos_kv values " + ", ".join(
        f"({i}, {i * 3})" for i in range(64)))
    # one hot standby per DN, registered as a read replica: the chaos
    # run exercises the replica serving tier (net/guard.py hwm gate)
    # under live DML + wire flaps, and the otbsnap certificate checks
    # its reads against the commit history
    from opentenbase_tpu.storage.replication import (DnStandbyServer,
                                                     HotStandby)
    rep_servers = []
    for i, srv in enumerate(servers):
        sb = HotStandby(os.path.join(tmp, f"chaos_sb_dn{i}"), index=i)
        rsrv = DnStandbyServer(sb).start()
        srv.node.attach_standby(rsrv.host, rsrv.port)
        cluster.register_read_replica(i, rsrv.host, rsrv.port,
                                      sb.datadir)
        rep_servers.append(rsrv)
    s.execute("set replica_reads = on")
    return cluster, gtm, servers + rep_servers


def _chaos_concurrent_arm():
    """--chaos-concurrent: the full otbshield acceptance run.  64
    client threads (56 through the coalescing scheduler on mixed
    Q1/Q3/agg/point ops, 8 point-reading a live TCP cluster) while a
    chaos driver injects, concurrently:

    - a poisoned literal (key 251) that kills any batched dispatch it
      rides in — bisection must fail ONLY the offender's queries and
      repeat offenses must trip the signature quarantine;
    - cancel storms (random sessions' cancel_event set mid-flight);
    - device OOM at dispatch (alternating recover-after-eviction and
      degrade-to-spill severities);
    - DN wire flaps on the TCP plane (otbguard retries absorb them);
    - shed pressure (queue_depth below the client count).

    Prints ONE JSON line: qps + p50/p99 over clean queries, the error
    split (offender_poison / offender_cancel / offender_timeout / shed
    vs collateral — collateral MUST be 0), wrong_results (MUST be 0),
    degraded count (injected OOM answers, not errors), and the slot /
    lease ledgers (MUST balance: zero leaks after drain).  Knobs:
    BENCH_CHAOSC_SECONDS (8), BENCH_CHAOSC_WARM_SECONDS (2),
    BENCH_CHAOSC_CLIENTS (64), BENCH_CHAOSC_SF (0.02),
    BENCH_CHAOSC_ANALYTICS=0 to drop Q1/Q3/Q5 for quick smoke runs."""
    import shutil
    import threading
    from opentenbase_tpu.exec import scheduler as sched_mod
    from opentenbase_tpu.exec import shield
    from opentenbase_tpu.exec.session import Session
    from opentenbase_tpu.exec.dist_session import ClusterSession
    from opentenbase_tpu.utils import faultinject as FI

    seconds = float(os.environ.get("BENCH_CHAOSC_SECONDS", "8"))
    warm_s = float(os.environ.get("BENCH_CHAOSC_WARM_SECONDS", "2"))
    n_clients = int(os.environ.get("BENCH_CHAOSC_CLIENTS", "64"))
    sf = float(os.environ.get("BENCH_CHAOSC_SF", "0.02"))
    analytics = os.environ.get("BENCH_CHAOSC_ANALYTICS", "1") != "0"
    # short cooldown so the quarantine trips AND lifts inside the run
    # (brownout-and-recover, not a permanent serial lane)
    os.environ.setdefault("OTB_SHIELD_COOLDOWN_S", "2")

    n_flap = max(1, min(8, n_clients // 8))
    n_sched = n_clients - n_flap

    # otbsnap: the chaos run doubles as the snapshot-visibility
    # acceptance shard — sanitizer live on every serve point, bounded
    # SI history recorded for the post-hoc G1/G-SI checker, and the
    # committed witness (analysis/visibility_witness.json) refreshed
    # from what this shard actually served
    from opentenbase_tpu.utils import snapcheck as snapcheck_mod
    os.environ.setdefault("OTB_SNAPCHECK", "1")
    os.environ.setdefault("OTB_SNAP_HISTORY", os.path.join(
        tempfile.gettempdir(), f"otb-chaosc-history-{os.getpid()}.json"))
    snapcheck_mod.reset()

    node, setup_s, _ = _qps_setup(sf)
    mixed = _chaosc_streams(analytics)
    poison_sql = "select v from qps_kv where k = 251"
    refs = {}
    for q in sorted(set(mixed + [poison_sql])):
        refs[q] = setup_s.execute(q)[-1].rows   # serial truth + compile

    tmp = tempfile.mkdtemp(prefix="otb-chaosc-")
    cluster, fgtm, servers = _chaosc_flap_cluster(tmp)

    sched_mod.reset_stats()
    shield.reset_stats()
    FI.arm_poison(251, times=-1)

    stats = {"ok": 0, "wrong": 0, "offender_poison": 0,
             "offender_cancel": 0, "offender_timeout": 0, "shed": 0,
             "collateral": 0}
    flap = {"ops": 0, "errors": 0, "wrong": 0}
    coll_samples = []
    lats = []
    sessions = []
    lock = threading.Lock()
    stop_at = [0.0]
    timed_from = [float("inf")]

    def classify(msg):
        if "poison-literal" in msg:
            return "offender_poison"
        if "user request" in msg:
            return "offender_cancel"
        if "statement timeout" in msg:
            return "offender_timeout"
        if "shed" in msg:
            return "shed"
        return "collateral"

    def sched_client(ci):
        sess = Session(node)
        with lock:
            sessions.append(sess)
        offender = ci % 7 == 0
        i = ci
        while time.perf_counter() < stop_at[0]:
            sql = (poison_sql if offender and i % 4 == 0
                   else mixed[i % len(mixed)])
            t0 = time.perf_counter()
            try:
                rows = sched.run(sess, sql)[-1].rows
                dt = time.perf_counter() - t0
                with lock:
                    if _rows_close(rows, refs[sql]):
                        stats["ok"] += 1
                    else:
                        stats["wrong"] += 1
                    if t0 >= timed_from[0]:
                        lats.append(dt)
            except Exception as e:  # noqa: BLE001 — the split IS the metric
                kind = classify(str(e))
                with lock:
                    stats[kind] += 1
                    if kind == "collateral" and len(coll_samples) < 3:
                        coll_samples.append(str(e)[:160])
            i += 1

    def flap_client(fi):
        fsess = ClusterSession(cluster)
        i = fi
        while time.perf_counter() < stop_at[0]:
            k = i % 64
            try:
                rows = fsess.query(f"select v from chaos_kv "
                                   f"where k = {k}")
                with lock:
                    flap["ops"] += 1
                    if rows != [(k * 3,)]:
                        flap["wrong"] += 1
            except Exception:  # noqa: BLE001 — collateral by definition
                with lock:
                    flap["ops"] += 1
                    flap["errors"] += 1
            i += 1

    def dml_client():
        # live write stream on the cluster plane, keys >= 1000 so the
        # verified point reads (k < 64) never see it — its job is to
        # move store versions + the replica hwm under the sanitizer
        # and to populate the SI history's write half
        dsess = ClusterSession(cluster)
        j = 0
        while time.perf_counter() < stop_at[0]:
            k = 1000 + (j % 50)
            try:
                if j % 2 == 0:
                    dsess.execute(
                        f"insert into chaos_kv values ({k}, {j})")
                else:
                    dsess.execute(
                        f"delete from chaos_kv where k = {k}")
            except Exception:  # noqa: BLE001 — flaps hit DML too
                pass
            j += 1
            time.sleep(0.02)

    def chaos_driver():
        n = 0
        while time.perf_counter() < stop_at[0]:
            time.sleep(0.4)
            n += 1
            with lock:
                live = list(sessions)
            if live:   # cancel storm: two victims per tick
                live[(n * 13) % len(live)].cancel_event.set()
                live[(n * 29) % len(live)].cancel_event.set()
            if n % 2 == 0:
                # OOM at dispatch: odd doses recover after eviction,
                # every 4th dose defeats the retry → spill degradation
                FI.arm_oom("dispatch", times=2 if n % 4 == 0 else 1)
            else:
                FI.arm_wire("dn0.recv", "close", times=2)

    # queue_depth below the client count: admission overflow IS the
    # shed-pressure injection (classified separately, never collateral)
    sched = sched_mod.Scheduler(node=node,
                                queue_depth=max(8, 3 * n_sched // 4),
                                max_batch=16)
    try:
        stop_at[0] = time.perf_counter() + warm_s + seconds
        timed_from[0] = time.perf_counter() + warm_s
        threads = ([threading.Thread(target=sched_client, args=(ci,),
                                     daemon=True)
                    for ci in range(n_sched)]
                   + [threading.Thread(target=flap_client, args=(fi,),
                                      daemon=True)
                      for fi in range(n_flap)]
                   + [threading.Thread(target=dml_client, daemon=True),
                      threading.Thread(target=chaos_driver,
                                       daemon=True)])
        t_begin = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_begin
        timed_wall = min(wall, seconds)
    finally:
        FI.disarm_poison()
        FI.disarm_oom()
        FI.disarm_wire()
        sched.stop()
        res = getattr(cluster, "_resolver", None)
        if res is not None:
            res.stop()
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        fgtm.stop()
        shutil.rmtree(tmp, ignore_errors=True)

    # otbsnap certificate: SI-check the recorded history, persist the
    # witnessed serve-point set into the committed witness file (the
    # lint gate cross-checks witnessed points against the statically
    # gated set)
    cert = _snap_certificate()
    snapcheck_mod.save_report()

    acq, rel = sched_mod.slot_balance()
    lst = sched.gtm.resq_stats()
    live_slots = sum(sched.gtm.resq_counts().values())
    sst = shield.stats_snapshot()
    dst = sched_mod.stats_snapshot()
    lats.sort()
    n_queries = sum(stats.values()) + flap["ops"]
    collateral = stats["collateral"] + flap["errors"]
    out = {
        "metric": f"chaos-concurrent p99 ({n_clients} clients, DN flap"
                  f" + cancel storm + OOM + poison, {platform})",
        "value": round(_qps_pct(lats, 0.99) * 1e3, 3),
        "unit": "ms",
        "clients": {"scheduler": n_sched, "flap": n_flap},
        "queries": n_queries,
        "qps": round(len(lats) / timed_wall, 1) if timed_wall else 0.0,
        "p50_ms": round(_qps_pct(lats, 0.50) * 1e3, 3),
        "p99_ms": round(_qps_pct(lats, 0.99) * 1e3, 3),
        "wrong_results": stats["wrong"] + flap["wrong"],
        "errors": {
            "offender_poison": stats["offender_poison"],
            "offender_cancel": stats["offender_cancel"],
            "offender_timeout": stats["offender_timeout"],
            "shed": stats["shed"],
            "collateral": collateral,
        },
        "collateral_rate": round(collateral / max(1, n_queries), 6),
        "degraded": sst["degraded"],
        "oom_dispatches": sst["oom_dispatches"],
        "oom_retries": sst["oom_retries"],
        "batch_failures": sst["batch_failures"],
        "isolated": sst["isolated"],
        "quarantined": sst["quarantined"],
        "batch_rate": round(dst["batched"] / dst["admitted"], 3)
        if dst["admitted"] else 0.0,
        "slot_ledger": {"acquired": acq, "released": rel,
                        "leaked": acq - rel},
        "gtm_leases": {**lst, "live_slots": live_slots},
        "flap": dict(flap),
        "snapshot_soundness": cert,
    }
    if coll_samples:
        out["collateral_samples"] = coll_samples
    if tpu_unavailable:
        out["tpu_unavailable"] = True
    print(json.dumps(out))
    ok = (collateral == 0 and out["wrong_results"] == 0
          and acq == rel and live_slots == 0
          and lst["acquired"] == lst["released"] + lst["expired"]
          and cert["si_anomalies"] == 0
          and cert["snapcheck_violations"] == 0)
    print(f"# chaos-concurrent: {'PASS' if ok else 'FAIL'} "
          f"(collateral={collateral} wrong={out['wrong_results']} "
          f"slots {acq}/{rel} si={cert['si_anomalies']} "
          f"snapviol={cert['snapcheck_violations']} leases {lst})",
          file=sys.stderr)
    if not ok:
        sys.exit(1)


def _phases(qs):
    """Span-tree phase breakdown of the arm's last warm run
    (session.last_query_stats(); all zeros when OTB_TRACE=0)."""
    return {k: round(float(qs.get(k, 0.0)), 3)
            for k in ("plan_ms", "stage_ms", "execute_ms",
                      "exchange_ms", "finalize_ms")}


def _dump_trace(cfg):
    """--trace: full last-query span tree, one JSON line on stderr
    (stdout stays the single bench JSON line).  Cluster runs include
    the piggy-backed remote DN/GTM subtrees — obs/xray.py grafts them
    into the CN tree before the trace reaches the ring."""
    if not TRACE_DUMP:
        return
    from opentenbase_tpu.obs import trace as obs_trace
    qt = obs_trace.last_trace()
    if qt is not None:
        print(json.dumps({"trace_for": cfg, **qt.to_dict()}),
              file=sys.stderr)


def _latency_block():
    """p50/p95/p99 per tier from the otb_query_ms histograms — the
    registry aggregates EVERY query the process ran, not just the
    min-of-warm arms the ladder reports."""
    from opentenbase_tpu.obs.metrics import REGISTRY
    out = {}
    for name, labels, kind, value in REGISTRY.samples():
        if kind != "histogram" or \
                not name.startswith("otb_query_ms_"):
            continue
        tag = name[len("otb_query_ms_"):]
        if tag not in ("count", "p50", "p95", "p99"):
            continue
        lbl = ",".join(f"{k}={v}" for k, v in labels) or "all"
        out.setdefault(lbl, {})[tag] = (
            int(value) if tag == "count" else round(float(value), 3))
    return out


def _wait_snapshot():
    """(event -> (count, total_ms)) snapshot of the cumulative
    wait-event registry, so arms can report their own deltas."""
    from opentenbase_tpu.obs import xray
    return {ev: (cnt, tot) for ev, cnt, tot, _p50, _p95, _p99
            in xray.wait_rows()}


def _wait_block(w0=None):
    """Where this arm's threads actually blocked: top-5 wait events by
    total stalled ms (delta against the `w0` snapshot when given) with
    the cumulative p50/p95/p99 per event — the bench-side twin of the
    otb_wait_events view."""
    from opentenbase_tpu.obs import xray
    w0 = w0 or {}
    rows = []
    for ev, cnt, tot, p50, p95, p99 in xray.wait_rows():
        c0, t0 = w0.get(ev, (0, 0.0))
        if cnt - c0 <= 0:
            continue
        rows.append((tot - t0, ev, cnt - c0, p50, p95, p99))
    rows.sort(reverse=True)
    return {ev: {"count": cnt, "total_ms": round(tot, 3),
                 "p50_ms": round(p50, 3), "p95_ms": round(p95, 3),
                 "p99_ms": round(p99, 3)}
            for tot, ev, cnt, p50, p95, p99 in rows[:5]}


def _mat_counters(x0, x1):
    """Ladder-entry materialization telemetry: deferred vs. eager
    column-gathers and bytes gathered between two exec_stats snapshots
    (exec/executor.py EXEC_STATS; trace-time counts for compiled
    tiers)."""
    return {
        "mat_deferred_cols": x1["deferred_cols"] - x0["deferred_cols"],
        "mat_eager_cols": x1["eager_cols"] - x0["eager_cols"],
        "mat_cols_gathered": x1["cols_materialized"]
        - x0["cols_materialized"],
        "mat_bytes_gathered": x1["bytes_materialized"]
        - x0["bytes_materialized"],
        "join_host_syncs": x1["host_syncs"] - x0["host_syncs"],
    }


def _compile_snapshot():
    """Total (programs_compiled, compile_ms) across every plancache
    tier — the otb_plancache counters the arms report as deltas so a
    compile storm is visible per-arm in the perf trajectory."""
    from opentenbase_tpu.exec import plancache
    c, ms = 0, 0.0
    for _t, _h, _m, comp, cms, _e, _l in plancache.stats():
        c += comp
        ms += cms
    return c, ms


def _compile_counters(c0, c1):
    return {"programs_compiled": c1[0] - c0[0],
            "compile_ms": round(c1[1] - c0[1], 3)}


def _residency_block():
    """Compressed-residency telemetry (storage/codec.py): what the
    live pool entries would occupy UNENCODED (bytes_logical) vs the
    actual post-encoding device bytes (bytes_resident) — their ratio
    is the effective device-cache multiplier the codecs buy."""
    from opentenbase_tpu.storage.bufferpool import POOL
    t = POOL.totals()
    res = t["bytes_live"]
    return {"bytes_logical": t["bytes_logical"],
            "bytes_resident": res,
            "effective_cache_ratio": round(t["bytes_logical"] / res, 3)
            if res else 0.0}


def _save_data(data, path):
    np.savez(path, **{f"{t}::{c}": v for t, cols in data.items()
                      for c, v in cols.items()})


def _load_data(path):
    z = np.load(path, allow_pickle=True)
    out = {}
    for k in z.files:
        t, c = k.split("::", 1)
        v = z[k]
        if v.dtype.kind in "UO":
            # datagen hands TEXT columns over as python lists; an
            # ndarray takes encode_column's sorted-unique dictionary
            # path, which would bake DIFFERENT dictionary orders into
            # the XLA programs and defeat the warm2 cache comparison
            v = v.tolist()
        out.setdefault(t, {})[c] = v
    return out


def _mesh_session(data):
    from opentenbase_tpu.exec.dist_session import ClusterSession
    from opentenbase_tpu.parallel.cluster import Cluster
    ndn = max(len(jax.devices()), 1)
    s = ClusterSession(Cluster(n_datanodes=ndn))
    from opentenbase_tpu.tpch.schema import SCHEMA
    s.execute(SCHEMA)
    for tname in ("region", "nation", "supplier", "customer", "part",
                  "partsupp", "orders", "lineitem"):
        td = s.cluster.catalog.table(tname)
        n = len(next(iter(data[tname].values())))
        s._insert_rows(td, data[tname], n)
    return s


def _warm2_child():
    """Fresh-process arm: same data, same persistent compile cache dir
    (inherited via OTB_COMPILE_CACHE) — measures what a RESTARTED
    cluster pays for its first queries AFTER the startup warmup ran
    (warm_statement feeds the hot statements to the background warmer;
    with the populated XLA cache the warmup itself is cheap)."""
    from opentenbase_tpu.exec import plancache
    from opentenbase_tpu.tpch import datagen
    from opentenbase_tpu.tpch.queries import Q
    data_path = os.environ.get("BENCH_DATA", "")
    if data_path and os.path.exists(data_path):
        data = _load_data(data_path)
    else:
        data = datagen.generate(sf=float(os.environ.get("BENCH_SF",
                                                        "1.0")))
    s = _mesh_session(data)
    t0 = time.perf_counter()
    for qn in (1, 3, 5):
        s.warm_statement(Q[qn])
    plancache.warm_drain(timeout=1200)
    warmup_ms = (time.perf_counter() - t0) * 1e3
    out = {"warmup_ms": warmup_ms}
    for qn in (1, 3, 5):
        c0 = _compile_snapshot()
        eng, cold = _time(lambda: s.query(Q[qn]), 1)
        out[f"Q{qn}"] = {"cold_ms": cold * 1e3,
                         "engine_ms": eng * 1e3,
                         "stage_ms": s.last_stage_ms,
                         "tier": s.last_tier,
                         **_compile_counters(c0, _compile_snapshot())}
    print(json.dumps({"warm2": out}))


def _run_warm2(data, sf):
    """Spawn the fresh-process arm; returns {Qn: {...}} or None."""
    fd, data_path = tempfile.mkstemp(suffix=".npz", prefix="otb-bench-")
    os.close(fd)
    try:
        _save_data(data, data_path)
        env = dict(os.environ)
        env.update({"BENCH_WARM2_CHILD": "1", "BENCH_DATA": data_path,
                    "BENCH_SF": str(sf), "BENCH_OLTP": "0"})
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=1800)
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line).get("warm2")
        print(f"# warm2 child produced no JSON (rc={proc.returncode}): "
              f"{proc.stderr[-300:]}", file=sys.stderr)
        return None
    except Exception as e:   # noqa: BLE001 — warm2 must not kill bench
        print(f"# warm2 arm failed: {e}", file=sys.stderr)
        return None
    finally:
        try:
            os.remove(data_path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# BENCH_MODE=qps — the serving-tier sustained-throughput arm
# ---------------------------------------------------------------------------

def _qps_pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return float(sorted_vals[idx])


def _qps_queries():
    """Three SQL streams, all same-signature-friendly to different
    degrees.  point_sig: point SELECTs with a varying key literal —
    every query masks to ONE tiny fused program, so per-query host
    overhead dominates and coalescing amortizes it (the decisive
    batching demonstration; on a 1-core CPU host the analytics shapes
    are compute-bound and batching can only tie serial).  q1_sig: Q1
    with a varying shipdate literal — one analytics signature.  mixed:
    Q1 variants + Q3 + Q5 + point SELECTs — several signatures plus
    join shapes."""
    from opentenbase_tpu.tpch.queries import Q
    base = Q[1].replace("date '1998-12-01' - interval '90' day",
                        "date '{}'")
    same = [base.format(f"1998-{m:02d}-{d:02d}")
            for m in (7, 8, 9) for d in (2, 9, 16, 23)]
    points = [f"select v from qps_kv where k = {(i * 37) % 400}"
              for i in range(64)]
    mixed = []
    for i in range(16):
        mixed.append(same[i % len(same)])
        if i % 4 == 0:
            mixed.append(Q[3])
        if i % 8 == 0:
            mixed.append(Q[5])
        mixed.append(points[i % len(points)])
    return points, same, mixed


def _qps_setup(sf):
    from opentenbase_tpu.exec.session import LocalNode, Session
    from opentenbase_tpu.tpch import datagen
    from opentenbase_tpu.tpch.schema import SCHEMA
    data = datagen.generate(sf=sf)
    node = LocalNode()
    s = Session(node)
    s.execute(SCHEMA)
    for tname in ("region", "nation", "supplier", "customer",
                  "orders", "lineitem"):
        td = node.catalog.table(tname)
        nn = len(next(iter(data[tname].values())))
        s._insert_rows(td, node.stores[tname], data[tname], nn)
    s.execute("create table qps_kv (k bigint, v bigint)")
    rows = ", ".join(f"({i}, {i * 7})" for i in range(400))
    s.execute(f"insert into qps_kv values {rows}")
    return node, s, len(data["lineitem"]["l_orderkey"])


def _qps_serial(node, stream, n):
    """Serial-loop baseline: one session, one query at a time — the
    number the scheduler arms must beat on sustained throughput."""
    from opentenbase_tpu.exec.session import Session
    s = Session(node)
    lats = []
    t_begin = time.perf_counter()
    for i in range(n):
        t0 = time.perf_counter()
        s.execute(stream[i % len(stream)])
        lats.append(time.perf_counter() - t0)
    wall = time.perf_counter() - t_begin
    lats.sort()
    return {"clients": 1, "queries": n, "qps": n / wall,
            "p50_ms": _qps_pct(lats, 0.50) * 1e3,
            "p99_ms": _qps_pct(lats, 0.99) * 1e3}


def _qps_drive(sched, node, stream, clients, seconds):
    """Closed-loop load: `clients` threads, each its own Session over
    the shared node, issuing through the scheduler back-to-back.
    Returns (merged latencies s, shed count, wall s)."""
    import threading
    from opentenbase_tpu.exec.session import Session
    lats = [[] for _ in range(clients)]
    sheds = [0] * clients
    stop_at = [0.0]
    gate = threading.Barrier(clients + 1)

    def client(ci):
        s = Session(node)
        i = ci
        gate.wait()
        while time.perf_counter() < stop_at[0]:
            t0 = time.perf_counter()
            try:
                sched.run(s, stream[i % len(stream)])
                lats[ci].append(time.perf_counter() - t0)
            except Exception:
                sheds[ci] += 1
            i += 1

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    for t in threads:
        t.start()
    stop_at[0] = time.perf_counter() + seconds
    t_begin = time.perf_counter()
    gate.wait()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_begin
    merged = sorted(x for per in lats for x in per)
    return merged, sum(sheds), wall


def _qps_arm(name, node, stream, clients, seconds, warm_s):
    from opentenbase_tpu.exec import scheduler as sched_mod
    sched = sched_mod.Scheduler(node=node,
                                queue_depth=max(128, 4 * clients))
    try:
        if warm_s > 0:   # untimed phase: batch-class compiles land here
            _qps_drive(sched, node, stream, clients, warm_s)
        s0 = sched_mod.stats_snapshot()
        c0 = _compile_snapshot()
        wv0 = _wait_snapshot()
        lats, shed, wall = _qps_drive(sched, node, stream, clients,
                                      seconds)
        c1 = _compile_snapshot()
        s1 = sched_mod.stats_snapshot()
    finally:
        sched.stop()
    admitted = s1["admitted"] - s0["admitted"]
    batched = s1["batched"] - s0["batched"]
    hist = {k: s1["hist"].get(k, 0) - s0["hist"].get(k, 0)
            for k in s1["hist"]
            if s1["hist"].get(k, 0) > s0["hist"].get(k, 0)}
    # otbpipe: what fraction of THIS arm's staging work the two-stage
    # pipeline hid behind device compute (delta, not lifetime ratio)
    stage_work = s1["stage_work_ms"] - s0["stage_work_ms"]
    stage_overlap = s1["stage_overlap_ms"] - s0["stage_overlap_ms"]
    return {"arm": name, "clients": clients, "replicas": 0,
            "queries": len(lats),
            "overlap_ratio": stage_overlap / stage_work
            if stage_work > 0 else 0.0,
            "pipelined": s1["pipelined_dispatches"]
            - s0["pipelined_dispatches"],
            "qps": len(lats) / wall if wall > 0 else 0.0,
            "p50_ms": _qps_pct(lats, 0.50) * 1e3,
            "p99_ms": _qps_pct(lats, 0.99) * 1e3,
            "shed": shed,
            "batch_rate": batched / admitted if admitted else 0.0,
            "batch_dispatches": s1["batch_dispatches"]
            - s0["batch_dispatches"],
            "batch_hist": " ".join(f"{k}:{v}"
                                   for k, v in sorted(hist.items())),
            "wait_events": _wait_block(wv0),
            **_compile_counters(c0, c1)}


def _qps_zipf_arm(node, clients, seconds, warm_s):
    """otbshare rung (b) under dashboard-shaped load: a zipfian-skewed
    pool of repeated statements (rank r drawn with p ~ r^-skew), every
    response verified against its serially-computed answer.  The
    sublinearity proof is `dispatches`: device dispatches stay near
    the DISTINCT statement count while served queries scale with the
    client count — repeats are CN memory hits that never touch the
    device."""
    import threading

    import numpy as np
    from opentenbase_tpu.exec import scheduler as sched_mod
    from opentenbase_tpu.exec import share as share_mod
    from opentenbase_tpu.exec.session import Session

    # otbsnap: record the SI history for this arm — every cache hit
    # lands as a read with its exact GTS-versioned key material, every
    # producing execution as a primary read, so the post-hoc checker
    # certifies result-cache serving against snapshot isolation
    from opentenbase_tpu.utils import snapcheck as snapcheck_mod
    hist_preset = bool(os.environ.get("OTB_SNAP_HISTORY", "").strip())
    if not hist_preset:
        os.environ["OTB_SNAP_HISTORY"] = os.path.join(
            tempfile.gettempdir(),
            f"otb-zipf-history-{os.getpid()}.json")
    snapcheck_mod.reset()

    n_distinct = int(os.environ.get("BENCH_QPS_ZIPF_DISTINCT", "48"))
    skew = float(os.environ.get("BENCH_QPS_ZIPF_SKEW", "1.2"))
    pool = [f"select sum(v), count(*) from qps_kv "
            f"where k < {13 * (r + 1)}" for r in range(n_distinct)]
    rng = np.random.default_rng(31)
    w = 1.0 / np.arange(1, n_distinct + 1) ** skew
    stream = [pool[i] for i in
              rng.choice(n_distinct, size=4096, p=w / w.sum())]
    expect = {}
    s = Session(node)
    for q in pool:                       # compile once + golden answers
        expect[q] = s.execute(q)[-1].rows

    lats = [[] for _ in range(clients)]
    wrong = [0] * clients
    sheds = [0] * clients
    stop_at = [0.0]

    def drive(sched, secs):
        gate = threading.Barrier(clients + 1)

        def client(ci):
            cs = Session(node)
            i = ci
            gate.wait()
            while time.perf_counter() < stop_at[0]:
                q = stream[i % len(stream)]
                t0 = time.perf_counter()
                try:
                    rows = sched.run(cs, q)[-1].rows
                    lats[ci].append(time.perf_counter() - t0)
                    if rows != expect[q]:
                        wrong[ci] += 1
                except Exception:
                    sheds[ci] += 1
                i += 1

        threads = [threading.Thread(target=client, args=(ci,),
                                    daemon=True)
                   for ci in range(clients)]
        for t in threads:
            t.start()
        stop_at[0] = time.perf_counter() + secs
        t_begin = time.perf_counter()
        gate.wait()
        for t in threads:
            t.join()
        return time.perf_counter() - t_begin

    sched = sched_mod.Scheduler(node=node,
                                queue_depth=max(128, 4 * clients))
    try:
        if warm_s > 0:
            drive(sched, warm_s)
        for per in lats:
            per.clear()
        wrong[:] = [0] * clients
        sheds[:] = [0] * clients
        s0 = sched_mod.stats_snapshot()
        w0 = share_mod.stats_snapshot()
        wv0 = _wait_snapshot()
        wall = drive(sched, seconds)
        s1 = sched_mod.stats_snapshot()
        w1 = share_mod.stats_snapshot()
    finally:
        sched.stop()
    cert = _snap_certificate()
    if not hist_preset:
        os.environ.pop("OTB_SNAP_HISTORY", None)
    merged = sorted(x for per in lats for x in per)
    hits = w1["result_cache_hits"] - w0["result_cache_hits"]
    misses = w1["result_cache_misses"] - w0["result_cache_misses"]
    return {"arm": "zipf_cache", "clients": clients, "replicas": 0,
            "si_anomalies": cert["si_anomalies"],
            "snapshot_soundness": cert,
            "queries": len(merged),
            "qps": len(merged) / wall if wall > 0 else 0.0,
            "p50_ms": _qps_pct(merged, 0.50) * 1e3,
            "p99_ms": _qps_pct(merged, 0.99) * 1e3,
            "shed": sum(sheds),
            "wrong": sum(wrong),
            "distinct": n_distinct, "skew": skew,
            "dispatches": s1["dispatches"] - s0["dispatches"],
            "cache_hits": hits,
            "cache_hit_rate": hits / (hits + misses)
            if hits + misses else 0.0,
            "fanin": w1["shared_scan_fanin"] - w0["shared_scan_fanin"],
            "wait_events": _wait_block(wv0)}


def _replica_counter(prefix):
    from opentenbase_tpu.obs.metrics import REGISTRY
    total = 0.0
    for line in REGISTRY.text().splitlines():
        if line.startswith(prefix) and not line.startswith("#"):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _qps_replica_setup(n_replicas, tmpdir):
    """A 2-DN cluster with `n_replicas` hot standbys per DN registered
    as read replicas (0 = primary-only baseline)."""
    from opentenbase_tpu.exec.dist_session import ClusterSession
    from opentenbase_tpu.parallel.cluster import Cluster
    from opentenbase_tpu.storage.replication import (DnStandbyServer,
                                                     HotStandby)
    cl = Cluster(n_datanodes=2,
                 datadir=os.path.join(tmpdir, f"cl_r{n_replicas}"))
    s = ClusterSession(cl)
    s.execute("create table rkv (k bigint primary key, v bigint)"
              " distribute by shard(k)")
    rows = ", ".join(f"({i}, {i * 7})" for i in range(400))
    s.execute(f"insert into rkv values {rows}")
    servers = []
    for rep in range(n_replicas):
        for i, dn in enumerate(cl.datanodes):
            sb = HotStandby(
                os.path.join(tmpdir, f"sb_r{n_replicas}_{rep}_dn{i}"),
                index=i)
            srv = DnStandbyServer(sb).start()
            dn.attach_standby(srv.host, srv.port)
            cl.register_read_replica(i, srv.host, srv.port, sb.datadir)
            servers.append(srv)
    if n_replicas:
        s.execute("set replica_reads = on")
    return cl, servers


def _qps_replica_arm(n_replicas, clients, seconds, tmpdir):
    """Closed-loop snapshot point reads over the cluster; every result
    is checked against the known v = 7k ground truth — routing to a
    standby must NEVER change an answer (wrong is asserted 0)."""
    import threading
    from opentenbase_tpu.exec.dist_session import ClusterSession
    cl, servers = _qps_replica_setup(n_replicas, tmpdir)
    routed0 = _replica_counter("otb_replica_reads_total")
    fall0 = _replica_counter("otb_replica_fallthrough_total")
    wv0 = _wait_snapshot()
    lats = [[] for _ in range(clients)]
    wrong = [0] * clients
    stop_at = [0.0]
    gate = threading.Barrier(clients + 1)

    def client(ci):
        s = ClusterSession(cl)
        i = ci
        gate.wait()
        while time.perf_counter() < stop_at[0]:
            k = (i * 37) % 400
            t0 = time.perf_counter()
            r = s.query(f"select v from rkv where k = {k}")
            lats[ci].append(time.perf_counter() - t0)
            if r != [(k * 7,)]:
                wrong[ci] += 1
            i += 1

    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(clients)]
    for t in threads:
        t.start()
    stop_at[0] = time.perf_counter() + seconds
    t_begin = time.perf_counter()
    gate.wait()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_begin
    for srv in servers:
        srv.stop()
    merged = sorted(x for per in lats for x in per)
    n_wrong = sum(wrong)
    assert n_wrong == 0, f"replica routing changed {n_wrong} answers"
    return {"arm": "replica_point", "clients": clients,
            "replicas": n_replicas, "queries": len(merged),
            "qps": len(merged) / wall if wall > 0 else 0.0,
            "p50_ms": _qps_pct(merged, 0.50) * 1e3,
            "p99_ms": _qps_pct(merged, 0.99) * 1e3,
            "wrong": n_wrong,
            "routed_reads":
                _replica_counter("otb_replica_reads_total") - routed0,
            "fallthrough":
                _replica_counter("otb_replica_fallthrough_total")
                - fall0,
            "wait_events": _wait_block(wv0)}


def _qps_mode():
    sf = float(os.environ.get("BENCH_SF", "0.02"))
    seconds = float(os.environ.get("BENCH_QPS_SECONDS", "4"))
    warm_s = float(os.environ.get("BENCH_QPS_WARM_SECONDS", "2"))
    clients_list = [int(c) for c in os.environ.get(
        "BENCH_QPS_CLIENTS", "8,64,256").split(",") if c.strip()]
    baseline_n = int(os.environ.get("BENCH_QPS_BASELINE_N", "60"))
    node, s, n_rows = _qps_setup(sf)
    points, same, mixed = _qps_queries()
    serial = {}
    arms = []
    for name, stream in (("point_sig", points), ("q1_sig", same),
                         ("mixed", mixed)):
        for q in sorted(set(stream)):   # compile every serial shape once
            s.execute(q)
        serial[name] = _qps_serial(node, stream, baseline_n)
        for clients in clients_list:
            arms.append(_qps_arm(name, node, stream, clients, seconds,
                                 warm_s))
    # work-sharing axis (otbshare): zipfian repeated statements — the
    # dispatch count must stay near the distinct-statement count while
    # served queries scale with clients (result-cache sublinearity)
    for clients in clients_list:
        arms.append(_qps_zipf_arm(node, clients, seconds, warm_s))
    # standby read scale-out axis: same point-read stream over a
    # cluster, replicas=0 (primary only) vs replicas=N hot standbys
    replicas_list = [int(r) for r in os.environ.get(
        "BENCH_QPS_REPLICAS", "0,2").split(",") if r.strip() != ""]
    if replicas_list:
        import tempfile
        rep_clients = clients_list[-1] if clients_list else 64
        with tempfile.TemporaryDirectory() as tmpdir:
            for n_rep in replicas_list:
                arms.append(_qps_replica_arm(n_rep, rep_clients,
                                             seconds, tmpdir))
    pick = [a for a in arms if a["arm"] == "point_sig"]
    head = next((a for a in pick if a["clients"] == 64),
                (pick or arms)[-1])
    out = {
        "metric": f"sustained QPS SF{sf:g} (point_sig, "
                  f"{head['clients']} clients, {platform})",
        "value": round(head["qps"], 1),
        "unit": "qps",
        "vs_baseline": round(head["qps"] / serial["point_sig"]["qps"], 3)
        if serial["point_sig"]["qps"] else 0.0,
        "schema": "serial: per-workload single-session loop "
                  "{clients, queries, qps, p50_ms, p99_ms}; arms: "
                  "per (workload, client-count) scheduler run "
                  "{arm, clients, replicas, queries, qps, p50_ms, "
                  "p99_ms, batch_rate = batched/admitted, "
                  "batch_dispatches, batch_hist 'size:count ...', "
                  "shed, overlap_ratio = staged-behind-compute ms / "
                  "staging ms, pipelined}; zipf_cache arms: zipfian "
                  "repeated statements through the GTS-versioned "
                  "result cache {distinct, skew, dispatches (device "
                  "dispatches — sublinear vs clients), cache_hits, "
                  "cache_hit_rate, fanin, wrong (asserted 0)}; "
                  "replica_point arms: cluster "
                  "point reads {replicas = hot standbys per DN, wrong "
                  "(asserted 0), routed_reads, fallthrough}; "
                  "vs_baseline = headline qps / serial point_sig qps",
        "serial": {k: {f: (round(v, 3) if isinstance(v, float) else v)
                       for f, v in e.items()} for k, e in serial.items()},
        "arms": [{k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in e.items()} for e in arms],
        "lineitem_rows": n_rows,
    }
    if tpu_unavailable:
        out["tpu_unavailable"] = True
    print(json.dumps(out))
    print(f"# qps mode: sf={sf} seconds={seconds} warm={warm_s} "
          f"clients={clients_list} platform={platform}",
          file=sys.stderr)


def main():
    if CHAOS_CONCURRENT:
        _chaos_concurrent_arm()
        return
    if CHAOS:
        _chaos_arm()
        return
    if OOB:
        _oob_arm()
        return
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    repeat = int(os.environ.get("BENCH_REPEAT", "5"))
    mode = os.environ.get("BENCH_MODE", "ladder")
    if mode not in ("ladder", "single", "mesh", "qps"):
        print(f"unknown BENCH_MODE={mode!r} (ladder|single|mesh|qps)",
              file=sys.stderr)
        sys.exit(2)

    # persistent XLA compilation cache: the first run populates it, the
    # warm2 child (and any real restart) reads compiled programs back
    from opentenbase_tpu.exec import plancache
    if not os.environ.get("OTB_COMPILE_CACHE"):
        os.environ["OTB_COMPILE_CACHE"] = tempfile.mkdtemp(
            prefix="otb-bench-xla-")
    plancache.enable_persistent_cache()

    if os.environ.get("BENCH_WARM2_CHILD") == "1":
        _warm2_child()
        return

    if mode == "qps":
        _qps_mode()
        return

    from opentenbase_tpu.tpch import datagen
    from opentenbase_tpu.tpch.queries import Q
    from opentenbase_tpu.tpch.schema import SCHEMA

    t0 = time.time()
    data = datagen.generate(sf=sf)
    dfs = datagen.as_dataframes(data)
    n_rows = len(data["lineitem"]["l_orderkey"])
    gen_s = time.time() - t0

    ladder = []
    notes = []

    # ---- config 1: Q1/Q3/Q5 single node (fused fragment path: Q1 is
    # the scan+agg kernel program, Q3/Q5 are fused JOIN fragments —
    # late-materialized index-composition joins in one XLA program,
    # exec/fused.py) ----
    from opentenbase_tpu.exec.executor import exec_stats_snapshot
    controls = {1: _pandas_q1, 3: _pandas_q3, 5: _pandas_q5}
    if mode in ("ladder", "single"):
        from opentenbase_tpu.exec.session import LocalNode, Session
        node = LocalNode()
        s1 = Session(node)
        s1.execute(SCHEMA)
        for tname in ("region", "nation", "supplier", "customer",
                      "orders", "lineitem"):
            td = node.catalog.table(tname)
            nn = len(next(iter(data[tname].values())))
            s1._insert_rows(td, node.stores[tname], data[tname], nn)
        for qn in (1, 3, 5):
            x0 = exec_stats_snapshot()
            c0 = _compile_snapshot()
            eng, cold = _time(lambda: s1.query(Q[qn]), repeat)
            c1 = _compile_snapshot()
            x1 = exec_stats_snapshot()
            phases = _phases(s1.last_query_stats())
            _dump_trace(f"Q{qn} single")
            ctl, _ = _time(lambda: controls[qn](dfs),
                           max(2, repeat // 2))
            gb = _gb_touched(qn, data)
            entry = {"config": f"Q{qn} single", "engine_ms": eng * 1e3,
                     "cold_ms": cold * 1e3,
                     "mrows_s": n_rows / eng / 1e6,
                     "vs_pandas": ctl / eng,
                     "gb_touched": gb, "gb_per_s": gb / eng,
                     "phases": phases}
            entry.update(_mat_counters(x0, x1))
            entry.update(_compile_counters(c0, c1))
            ladder.append(entry)
        del s1, node

    # ---- config 2: Q1/Q3/Q5 through the device-mesh data plane ----
    mesh_q1 = None
    if mode in ("ladder", "mesh"):
        from opentenbase_tpu.storage.bufferpool import POOL
        ndn = max(len(jax.devices()), 1)
        s2 = _mesh_session(data)
        for qn in (1, 3, 5):
            x0 = exec_stats_snapshot()
            c0 = _compile_snapshot()
            eng, cold = _time(lambda: s2.query(Q[qn]), repeat)
            c1 = _compile_snapshot()
            x1 = exec_stats_snapshot()
            ctl, _ = _time(lambda: controls[qn](dfs), max(2, repeat // 2))
            gb = _gb_touched(qn, data)
            # warm-repeat arm: one more run against the populated
            # buffer pool — stage_ms should be ~0 and the pool hit
            # rate 100% (device_put of table columns skipped entirely)
            t0 = POOL.totals()
            t_run = time.perf_counter()
            s2.query(Q[qn])
            warm_ms = (time.perf_counter() - t_run) * 1e3
            t1 = POOL.totals()
            phases = _phases(s2.last_query_stats())
            _dump_trace(f"Q{qn} mesh")
            dh = t1["hits"] - t0["hits"]
            dm = t1["misses"] - t0["misses"]
            stage = s2.last_stage_ms
            entry = {"config": f"Q{qn} mesh x{ndn}",
                     "engine_ms": eng * 1e3,
                     "cold_ms": cold * 1e3,
                     "stage_ms": stage,
                     "compute_ms": max(warm_ms - stage, 0.0),
                     "pool_hit_rate": dh / max(dh + dm, 1),
                     "pool_staged_bytes": t1["uploaded_bytes"]
                     - t0["uploaded_bytes"],
                     "mrows_s_chip": n_rows / eng / 1e6 / ndn,
                     "vs_pandas": ctl / eng,
                     "gb_touched": gb,
                     "gb_per_s": gb / eng,
                     "tier": s2.last_tier,
                     "phases": phases}
            entry.update(_residency_block())
            entry.update(_mat_counters(x0, x1))
            entry.update(_compile_counters(c0, c1))
            if s2.last_tier != "mesh":
                entry["fallback"] = s2.last_fallback
            ladder.append(entry)
            if qn == 1:
                mesh_q1 = entry
        if os.environ.get("BENCH_OLTP", "1") != "0":
            c0 = _compile_snapshot()
            ins_p50, raw_p50, prep_p50 = _oltp_latencies(s2)
            entry = {"config": "point ops",
                     "insert_p50_ms": ins_p50,
                     "select_raw_p50_ms": raw_p50,
                     "select_prepared_p50_ms": prep_p50}
            entry.update(_compile_counters(c0, _compile_snapshot()))
            ladder.append(entry)

        # ---- warm-restart arm: a FRESH process against the populated
        # persistent compile cache; its first-query cold_ms lands in
        # the matching ladder entries as warm2_ms ----
        if os.environ.get("BENCH_WARM2", "1") != "0":
            warm2 = _run_warm2(data, sf)
            if warm2:
                wu = warm2.pop("warmup_ms", None)
                for entry in ladder:
                    cfg = str(entry.get("config", ""))
                    for qn, w in warm2.items():
                        if cfg.startswith(f"{qn} mesh"):
                            entry["warm2_ms"] = w["cold_ms"]
                            entry["warm2_x_engine"] = (
                                w["cold_ms"] / entry["engine_ms"]
                                if entry.get("engine_ms") else 0.0)
                if wu is not None:
                    ladder.append({"config": "warm restart",
                                   "warmup_ms": wu})

    # ---- optional: BASELINE config-2 scale (SF10) — opt-in via
    # BENCH_SF10=1.  NOT default: SF10 datagen alone takes ~1h on a
    # 1-core control box (measured 3694s); the committed SF10_RESULTS.md
    # records a full run.  On real multi-core TPU hosts set the env.
    if os.environ.get("BENCH_SF10", "0") == "1":
        try:
            from opentenbase_tpu.exec.dist_session import ClusterSession
            from opentenbase_tpu.parallel.cluster import Cluster
            data10 = datagen.generate(sf=10.0)
            n10 = len(data10["lineitem"]["l_orderkey"])
            s3 = ClusterSession(Cluster(
                n_datanodes=max(len(jax.devices()), 1)))
            s3.execute(SCHEMA)
            for tname in ("region", "nation", "supplier", "customer",
                          "part", "partsupp", "orders", "lineitem"):
                td = s3.cluster.catalog.table(tname)
                nn = len(next(iter(data10[tname].values())))
                s3._insert_rows(td, data10[tname], nn)
            for qn in (1, 3, 5):
                c0 = _compile_snapshot()
                eng, cold = _time(lambda: s3.query(Q[qn]), 2)
                entry = {"config": f"SF10 Q{qn}",
                         "engine_ms": eng * 1e3,
                         "cold_ms": cold * 1e3,
                         "mrows_s_chip": n10 / eng / 1e6,
                         "tier": s3.last_tier}
                entry.update(_compile_counters(c0, _compile_snapshot()))
                ladder.append(entry)
        except Exception as e:   # noqa: BLE001 — SF10 must not kill
            ladder.append({"config": "SF10", "error": str(e)[:200]})

    head = mesh_q1 or ladder[0]
    out = {
        "metric": f"TPC-H Q1 SF{sf:g} throughput "
                  f"({platform}, {head['config']})",
        "value": round(head.get("mrows_s", head.get("mrows_s_chip", 0))
                       * (1 if "mrows_s" in head
                          else max(len(jax.devices()), 1)), 3),
        "unit": "Mrows/s",
        "vs_baseline": round(head["vs_pandas"], 3),
        "ladder": [{k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in e.items()} for e in ladder],
        "plancache": [dict(zip(("tier", "hits", "misses", "compiles",
                                "compile_ms", "evictions", "live"), r))
                      for r in plancache.stats()],
        "latency": _latency_block(),
    }
    from opentenbase_tpu.storage.bufferpool import POOL
    out["buffercache"] = [
        dict(zip(("table", "hits", "misses", "bytes_live", "evictions",
                  "invalidations", "pinned", "pins", "unpins",
                  "bytes_logical", "bytes_resident"), r))
        for r in POOL.stats_rows()]
    if tpu_unavailable:
        out["tpu_unavailable"] = True
    print(json.dumps(out))
    print(f"# rows={n_rows} datagen={gen_s:.1f}s platform={platform} "
          f"mode={mode}", file=sys.stderr)


if __name__ == "__main__":
    main()
